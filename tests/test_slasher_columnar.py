"""Columnar slasher subsystem: differential fuzz vs the retained scalar
oracle (random streams incl. equivocations, prune-mid-stream,
restart-resume), chunked-span invariants, seeded-recall at mainnet
shape, the scalar-DB migration path, and the SLASHER_PROCESS
beacon_processor lane (queue-discipline thread check)."""

import random
import threading

import numpy as np
import pytest

from lighthouse_tpu.metrics import REGISTRY
from lighthouse_tpu.slasher import SlasherConfig
from lighthouse_tpu.slasher.columnar import (
    ColumnarSlasher,
    _attestation_data_roots,
)
from lighthouse_tpu.slasher.reference import ReferenceSlasher
from lighthouse_tpu.slasher.spans import (
    DISTANCE_CAP,
    SpanStore,
    UPDATE_WINDOW,
)
from lighthouse_tpu.store.kv import DBColumn, MemoryStore
from lighthouse_tpu.types.containers import build_types
from lighthouse_tpu.types.eth_spec import MinimalEthSpec as E

T = build_types(E)


def _att(indices, source, target, root=b"\x01" * 32):
    return T.IndexedAttestation(
        attesting_indices=indices,
        data=T.AttestationData(
            slot=target * E.SLOTS_PER_EPOCH,
            index=0,
            beacon_block_root=root,
            source=T.Checkpoint(epoch=source, root=b"\x01" * 32),
            target=T.Checkpoint(epoch=target, root=b"\x01" * 32),
        ),
        signature=b"\x00" * 96,
    )


def _fingerprint(slasher):
    """Drained emissions as bytes — the bit-identical comparison unit."""
    atts, props = slasher.drain_slashings()
    return (
        [(a.attestation_1.serialize(), a.attestation_2.serialize()) for a in atts],
        [
            (p.signed_header_1.serialize(), p.signed_header_2.serialize())
            for p in props
        ],
    )


def _random_stream(rng, epoch, n_items, n_validators=40):
    """Hostile mix: sane votes, duplicates, equivocations, inverted
    (target < source) shapes, stale and far-future epochs."""
    out = []
    for _ in range(n_items):
        src = rng.randrange(0, epoch + 3)
        tgt = rng.randrange(max(0, src - 2), epoch + 4)
        if rng.random() < 0.15:
            tgt = rng.randrange(0, epoch + 4)  # anything, incl. t < s
        if rng.random() < 0.05:
            src = rng.randrange(0, 2**40)  # far-future nonsense source
        idx = [rng.randrange(0, n_validators) for _ in range(rng.randrange(1, 6))]
        if rng.random() < 0.05:
            # hostile sparse validator id (must not grow resident columns)
            idx.append(rng.randrange(2**30, 2**45))
        out.append(_att(idx, src, tgt, bytes([rng.randrange(0, 4)]) * 32))
    return out


# ---------------------------------------------------------------------------
# differential fuzz: columnar ≡ scalar
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("seed", range(6))
def test_differential_fuzz_random_streams(seed):
    """Random hostile streams over many cycles with the epoch advancing
    (prune-mid-stream): stats AND serialized emissions are bit-identical
    between the columnar engine and the scalar oracle every cycle."""
    rng = random.Random(seed)
    c = ColumnarSlasher(E, SlasherConfig(history_length=12))
    r = ReferenceSlasher(E, SlasherConfig(history_length=12))
    epoch = 10
    for cycle in range(12):
        for a in _random_stream(rng, epoch, rng.randrange(0, 8)):
            c.accept_attestation(a)
            r.accept_attestation(a)
        sc = c.process_queued(epoch)
        sr = r.process_queued(epoch)
        assert sc == sr, (seed, cycle, sc, sr)
        assert _fingerprint(c) == _fingerprint(r), (seed, cycle)
        epoch += rng.randrange(0, 3)
    # record-state parity after prunes
    for v in range(40):
        for t in range(0, epoch + 5):
            assert c.has_attestation_record(v, t) == r.has_attestation_record(v, t)


def test_differential_restart_resume(tmp_path):
    """Mid-stream restart through a REAL KV store: both engines rebuilt
    from their stores keep emitting bit-identically on a hostile random
    stream. (Restarted runs may legitimately re-emit a slashing the
    unbroken run deduped — the `_emitted` set is rebuilt lazily by
    design, identically in both engines — so unbroken-equality is
    asserted separately on a stream whose conflicts are post-restart.)"""
    from lighthouse_tpu.store import open_item_store

    rng = random.Random(42)
    streams = []
    epoch = 10
    epochs = []
    for _ in range(8):
        streams.append(_random_stream(rng, epoch, rng.randrange(1, 6)))
        epochs.append(epoch)
        epoch += rng.randrange(0, 3)

    cs = open_item_store(str(tmp_path / "c.db"))
    rs = open_item_store(str(tmp_path / "r.db"))
    c = ColumnarSlasher(E, SlasherConfig(history_length=12), store=cs)
    r = ReferenceSlasher(E, SlasherConfig(history_length=12), store=rs)
    for cycle, (stream, ep) in enumerate(zip(streams, epochs)):
        if cycle == 4:  # crash + restart both persistent engines
            c = ColumnarSlasher(E, SlasherConfig(history_length=12), store=cs)
            r = ReferenceSlasher(E, SlasherConfig(history_length=12), store=rs)
        for a in stream:
            c.accept_attestation(a)
            r.accept_attestation(a)
        assert c.process_queued(ep) == r.process_queued(ep)
        assert _fingerprint(c) == _fingerprint(r), cycle
    cs.close()
    rs.close()


def test_restart_resume_bit_identical_to_unbroken(tmp_path):
    """When the slashable conflicts arrive only AFTER the restart (the
    common crash-recovery case), the restarted columnar run's detections
    are bit-identical to an unbroken run over the same stream — spans
    and records reload exactly."""
    from lighthouse_tpu.store import open_item_store

    # pre-restart: honest records only (targets strictly increasing)
    pre = [
        _att([1, 2, 3], 4, 5, b"\x0a" * 32),
        _att([2, 3, 4], 5, 6, b"\x0b" * 32),
        _att([9], 3, 7, b"\x0c" * 32),
    ]
    # post-restart: a double vote, and surrounds in both directions
    post = [
        _att([2], 5, 6, b"\x1b" * 32),  # double vs the (5, 6) record
        _att([9], 4, 6, b"\x1c" * 32),  # surrounded by the (3, 7) record
        _att([3], 3, 8, b"\x1d" * 32),  # surrounds the (5, 6) record
    ]
    store = open_item_store(str(tmp_path / "c.db"))
    c = ColumnarSlasher(E, store=store)
    unbroken = ColumnarSlasher(E)
    for a in pre:
        c.accept_attestation(a)
        unbroken.accept_attestation(a)
    assert c.process_queued(7) == unbroken.process_queued(7)
    c2 = ColumnarSlasher(E, store=store)  # crash + reload
    for a in post:
        c2.accept_attestation(a)
        unbroken.accept_attestation(a)
    assert c2.process_queued(8) == unbroken.process_queued(8)
    fp_restart, fp_unbroken = _fingerprint(c2), _fingerprint(unbroken)
    assert fp_restart == fp_unbroken
    assert len(fp_restart[0]) == 3
    store.close()


def test_dangling_record_dropped_on_reload():
    """A record row whose attestation body is missing (pruned/corrupt) is
    dropped on reload, exactly like the scalar engine."""
    ms = MemoryStore()
    c = ColumnarSlasher(E, store=ms)
    c.accept_attestation(_att([5], 1, 4, b"\x0a" * 32))
    c.process_queued(5)
    # corrupt: delete the body, keep the record row
    for key in ms.keys(DBColumn.SLASHER_INDEXED):
        ms.delete(DBColumn.SLASHER_INDEXED, key)
    c2 = ColumnarSlasher(E, store=ms)
    r2 = ReferenceSlasher(E, store=ms)
    assert not c2.has_attestation_record(5, 4)
    assert not r2.has_attestation_record(5, 4)
    assert c2.attestation_record_count() == 0


def test_scalar_db_migration_rebuilds_spans():
    """A DB written by the scalar engine has records but no span tiles:
    the columnar engine rebuilds the spans from the reloaded records and
    still detects surrounds in both directions."""
    ms = MemoryStore()
    r = ReferenceSlasher(E, store=ms)
    r.accept_attestation(_att([7], 2, 6, b"\x0a" * 32))
    r.accept_attestation(_att([9], 3, 5, b"\x0b" * 32))
    r.process_queued(7)
    assert ms.keys(DBColumn.SLASHER_MIN_SPAN) == []
    rebuilds0 = REGISTRY.counter("slasher_span_rebuilds_total").value()
    c = ColumnarSlasher(E, store=ms)
    assert REGISTRY.counter("slasher_span_rebuilds_total").value() == rebuilds0 + 1
    c.accept_attestation(_att([7], 1, 8, b"\x0c" * 32))  # surrounds (2, 6)
    c.accept_attestation(_att([9], 4, 4, b"\x0d" * 32))  # surrounded by (3, 5)
    out = c.process_queued(9)
    assert out["attester_slashings"] == 2


def test_columnar_restart_adopts_persisted_tiles():
    """A columnar-written DB reloads spans from tiles (no rebuild) and
    keeps detecting."""
    ms = MemoryStore()
    c1 = ColumnarSlasher(E, store=ms)
    c1.accept_attestation(_att([3], 2, 6, b"\x0a" * 32))
    c1.process_queued(7)
    assert ms.keys(DBColumn.SLASHER_MIN_SPAN)
    rebuilds0 = REGISTRY.counter("slasher_span_rebuilds_total").value()
    c2 = ColumnarSlasher(E, store=ms)
    assert REGISTRY.counter("slasher_span_rebuilds_total").value() == rebuilds0
    c2.accept_attestation(_att([3], 1, 8, b"\x0b" * 32))
    assert c2.process_queued(9)["attester_slashings"] == 1


# ---------------------------------------------------------------------------
# seeded recall at mainnet-like shape
# ---------------------------------------------------------------------------


def _flood(n_val, n_comm, source, target, seed, slot_base=None):
    rng = np.random.default_rng(seed)
    chunks = np.array_split(rng.permutation(n_val), n_comm)
    cp = T.Checkpoint(epoch=source, root=b"\x01" * 32)
    ct = T.Checkpoint(epoch=target, root=b"\x02" * 32)
    return [
        T.IndexedAttestation(
            attesting_indices=np.sort(ch).tolist(),
            data=T.AttestationData(
                slot=(slot_base or target * E.SLOTS_PER_EPOCH) + (i % 8),
                index=i // 8,
                beacon_block_root=b"\x03" * 32,
                source=cp,
                target=ct,
            ),
            signature=b"\x00" * 96,
        )
        for i, ch in enumerate(chunks)
    ]


def test_seeded_recall_in_honest_flood():
    """Planted offenders inside an honest 4k-validator flood: the double
    vote and BOTH surround directions are all found (100% recall), with
    zero false emissions, and the whole honest flood takes the columnar
    fast path (no exact scans beyond the planted candidates)."""
    n = 4096
    warm = _flood(n, 16, 9, 10, seed=1)
    flood = _flood(n, 16, 10, 11, seed=2)
    s = ColumnarSlasher(E)
    # victims: 100 (double), 200 (old record surrounds its flood vote),
    # 300 (attacker vote surrounds its warm record)
    for a in warm:
        s.accept_attestation(a)
    s.accept_attestation(_att([200], 8, 13, b"\xaa" * 32))
    s.accept_attestation(_att([300], 11, 12, b"\xbb" * 32))
    s.process_queued(10)
    scans0 = REGISTRY.counter("slasher_exact_scans_total").value()
    for a in flood:
        s.accept_attestation(a)
    s.accept_attestation(_att([100], 10, 11, b"\xcc" * 32))  # double vs flood
    s.accept_attestation(_att([300], 10, 13, b"\xdd" * 32))  # surrounds (11,12)
    out = s.process_queued(11)
    assert out["attester_slashings"] == 3
    atts, _ = s.drain_slashings()
    offenders = {
        int(
            (
                set(a.attestation_1.attesting_indices)
                & set(a.attestation_2.attesting_indices)
            ).pop()
        )
        for a in atts
    }
    assert offenders == {100, 200, 300}
    from lighthouse_tpu.state_processing.accessors import (
        is_slashable_attestation_data,
    )

    for a in atts:
        assert is_slashable_attestation_data(
            a.attestation_1.data, a.attestation_2.data
        )
    # filter precision: only the planted candidates were exact-scanned
    scans = REGISTRY.counter("slasher_exact_scans_total").value() - scans0
    assert scans <= 4, f"span filter leaked {scans} exact scans"


def test_recall_matches_reference_on_same_seeded_flood():
    n = 1024
    plan = [
        _flood(n, 8, 9, 10, seed=3),
        [_att([20], 8, 13, b"\xaa" * 32), _att([30], 11, 12, b"\xbb" * 32)],
        _flood(n, 8, 10, 11, seed=4),
        [_att([10], 10, 11, b"\xcc" * 32), _att([30], 10, 13, b"\xdd" * 32)],
    ]
    c, r = ColumnarSlasher(E), ReferenceSlasher(E)
    for engine in (c, r):
        for a in plan[0] + plan[1]:
            engine.accept_attestation(a)
        engine.process_queued(10)
        for a in plan[2] + plan[3]:
            engine.accept_attestation(a)
        engine.process_queued(11)
    assert _fingerprint(c) == _fingerprint(r)


# ---------------------------------------------------------------------------
# hostile shapes / internals
# ---------------------------------------------------------------------------


def test_dense_overlay_upgrade_matches_reference():
    """One cycle recording more rows than the dict threshold upgrades the
    pending overlay to dense arrays; merged lookups and detections stay
    identical to the oracle."""
    from lighthouse_tpu.slasher.columnar import _DENSE_THRESHOLD

    # 3 disjoint 2048-index aggregates: 6144 rows into ONE epoch store in
    # one cycle — past the dict threshold, so the overlay upgrades
    n_rows = 3 * 2048
    assert n_rows > _DENSE_THRESHOLD
    aggs = [
        _att(list(range(k * 2048, (k + 1) * 2048)), 3, 4, b"\x0a" * 32)
        for k in range(3)
    ]
    c, r = ColumnarSlasher(E), ReferenceSlasher(E)
    for engine in (c, r):
        for a in aggs:
            engine.accept_attestation(a)
        engine.process_queued(5)
        # next cycle probes the dense-merged base: doubles + a surround
        engine.accept_attestation(_att([17, 4000], 3, 4, b"\x0b" * 32))
        engine.accept_attestation(_att([5000], 2, 6, b"\x0c" * 32))
        engine.process_queued(6)
    assert c.attestation_record_count() == r.attestation_record_count() == n_rows + 1
    sc, sr = _fingerprint(c), _fingerprint(r)
    assert sc == sr
    assert len(sc[0]) == 3  # two doubles + one surround


def test_oversized_span_and_inverted_votes_match_reference():
    """Distance-cap overflow, inverted (t < s) records as surround
    witnesses, and duplicate indices within one attestation all route
    through the conservative paths and still match the oracle."""
    cases = [
        # inverted record (10, 3) later witnesses s' < s2 … predicate runs
        [_att([1], 10, 3, b"\x0a" * 32), _att([1], 4, 8, b"\x0b" * 32)],
        # huge-distance vote (cap overflow) then a surrounded vote
        [_att([2], 1, DISTANCE_CAP + 10, b"\x0c" * 32), _att([2], 3, 5, b"\x0d" * 32)],
        # window-capped max-span: wide surrounder, deep query
        [
            _att([3], 1, UPDATE_WINDOW + 300, b"\x0e" * 32),
            _att([3], UPDATE_WINDOW + 5, UPDATE_WINDOW + 6, b"\x0f" * 32),
        ],
        # duplicate indices within one hostile attestation
        [_att([4, 4, 4], 1, 5, b"\x1a" * 32), _att([4], 1, 5, b"\x1b" * 32)],
    ]
    for i, stream in enumerate(cases):
        c, r = ColumnarSlasher(E), ReferenceSlasher(E)
        for a in stream:
            c.accept_attestation(a)
            r.accept_attestation(a)
        # two cycles: first item recorded, second checked against it
        sc = c.process_queued(DISTANCE_CAP + 20)
        sr = r.process_queued(DISTANCE_CAP + 20)
        assert sc == sr, (i, sc, sr)
        assert _fingerprint(c) == _fingerprint(r), i


def test_span_store_invariants_after_fuzz():
    """Incremental span state is always at least as detection-aggressive
    as a fresh rebuild from the live records (no false negatives): for
    unguarded validators, incremental min ≤ rebuilt min and incremental
    max ≥ rebuilt max at every queryable epoch."""
    rng = random.Random(7)
    c = ColumnarSlasher(E, SlasherConfig(history_length=32))
    epoch = 20
    for _ in range(10):
        for a in _random_stream(rng, epoch, 6, n_validators=24):
            c.accept_attestation(a)
        c.process_queued(epoch)
        epoch += rng.randrange(0, 2)
    rebuilt = SpanStore(history_length=32)
    rebuilt.floor = c.spans.floor
    for target, es in c._epochs.items():
        for source in np.unique(es.base_source).tolist():
            rebuilt.record(
                es.base_v[es.base_source == source], int(source), target, epoch
            )
    vs = np.arange(24, dtype=np.int64)
    for e in range(c.spans.floor, epoch + 4):
        guard = c.spans.scan_guard_mask(vs, e) | rebuilt.scan_guard_mask(vs, e)
        ok_min = c.spans.gather_min(vs, e) <= rebuilt.gather_min(vs, e)
        ok_max = c.spans.gather_max(vs, e) >= rebuilt.gather_max(vs, e)
        assert bool(np.all(ok_min | guard)), e
        assert bool(np.all(ok_max | guard)), e


def test_batched_attestation_data_roots_match_ssz():
    import os

    rng = random.Random(0)
    datas = [
        T.AttestationData(
            slot=rng.randrange(0, 2**40),
            index=rng.randrange(0, 2**32),
            beacon_block_root=os.urandom(32),
            source=T.Checkpoint(epoch=rng.randrange(0, 2**50), root=os.urandom(32)),
            target=T.Checkpoint(epoch=rng.randrange(0, 2**50), root=os.urandom(32)),
        )
        for _ in range(65)
    ]
    for batch_root, d in zip(_attestation_data_roots(datas), datas):
        assert batch_root == d.hash_tree_root()


def test_span_tile_persistence_roundtrip():
    """Dirty tiles persist with exact granularity and reload into the
    same resident values."""
    ms = MemoryStore()
    st = SpanStore(kv=ms)
    vals = np.array([1, 2, 300, 5000], dtype=np.int64)
    st.record(vals, 8, 9, current_epoch=10)
    ops = st.flush_ops()
    ms.do_atomically(ops)
    put_tiles = [op for op in ops if op[0] == "put" and len(op[2]) == 16]
    # rows 1,2 share a validator chunk; 300 and 5000 are their own —
    # exactly 3 dirty tiles per touched side
    assert len(put_tiles) == 3
    st2 = SpanStore(kv=ms)
    assert np.array_equal(st2.gather_min(vals, 7), st.gather_min(vals, 7))
    assert np.array_equal(st2.gather_max(vals, 7), st.gather_max(vals, 7))


# ---------------------------------------------------------------------------
# SLASHER_PROCESS lane (queue discipline)
# ---------------------------------------------------------------------------


def test_slasher_process_rides_its_own_worktype_lane():
    """The epoch cycle submitted by the slot tick runs on a beacon
    processor WORKER thread — never a gossip reader or the caller — on
    the lowest-priority SLASHER_PROCESS lane, with its queue-wait/run
    histograms populated; the epoch claim dedups competing slot drivers."""
    from dataclasses import replace

    from lighthouse_tpu.beacon_chain.harness import BeaconChainHarness
    from lighthouse_tpu.beacon_processor import BeaconProcessor, WorkType
    from lighthouse_tpu.crypto import bls
    from lighthouse_tpu.slasher.service import SlasherService
    from lighthouse_tpu.types.chain_spec import minimal_spec

    # lowest-priority DUTY lane: only the store-migration housekeeping
    # lane (PR 20) sits below it — detection must not wait on pruning
    assert WorkType.SLASHER_PROCESS == max(
        t for t in WorkType if t is not WorkType.MIGRATE_STORE
    ), "must be lowest priority bar the migration housekeeping lane"
    assert WorkType.MIGRATE_STORE == max(WorkType)

    bls.set_backend("fake_crypto")
    spec = replace(minimal_spec(), altair_fork_epoch=0)
    h = BeaconChainHarness(spec, E, validator_count=16)
    svc = SlasherService(h.chain)
    proc = BeaconProcessor(num_workers=1, name="network_beacon_processor")
    seen_threads = []
    orig = svc.slasher.process_queued

    def instrumented(epoch):
        seen_threads.append(threading.current_thread().name)
        return orig(epoch)

    svc.slasher.process_queued = instrumented
    svc.observe_indexed_attestation(_att([3], 0, 1, b"\x0a" * 32))
    svc.observe_indexed_attestation(_att([3], 0, 1, b"\x0b" * 32))
    wait_hist = REGISTRY.histogram(
        "beacon_processor_queue_wait_seconds_slasher_process", ""
    )
    run_hist = REGISTRY.histogram(
        "beacon_processor_work_seconds_slasher_process", ""
    )
    waits0, runs0 = wait_hist.count, run_hist.count
    slot = 2 * E.SLOTS_PER_EPOCH
    assert svc.on_slot(slot, processor=proc) is None  # queued, not inline
    # competing driver for the SAME epoch: claim already taken, no dupe
    assert svc.on_slot(slot + 1, processor=proc) is None
    assert proc.drain(timeout=10)
    assert len(seen_threads) == 1, "epoch processed exactly once"
    assert seen_threads[0].startswith("network_beacon_processor-w")
    assert not seen_threads[0].startswith("gossip-")
    assert h.chain.op_pool._attester_slashings, "slashing not pooled"
    assert wait_hist.count == waits0 + 1
    assert run_hist.count == runs0 + 1
    # without a processor the next epoch still runs inline (timer-only)
    svc.observe_indexed_attestation(_att([5], 1, 2, b"\x0a" * 32))
    svc.observe_indexed_attestation(_att([5], 1, 2, b"\x0b" * 32))
    stats = svc.on_slot(3 * E.SLOTS_PER_EPOCH)
    assert stats is not None and stats["attester_slashings"] == 1
    assert seen_threads[-1] == threading.current_thread().name
    proc.shutdown()


def test_scalar_interlude_triggers_span_rebuild():
    """Regression (review): a kill-switch interlude — scalar engine
    recording attestations into a columnar-written DB — leaves the span
    tiles STALE. The record-set fingerprint catches it on reload and
    rebuilds, so the interlude-era surround is still detected."""
    ms = MemoryStore()
    c1 = ColumnarSlasher(E, store=ms)
    c1.accept_attestation(_att([4], 5, 6, b"\x0a" * 32))
    c1.process_queued(7)  # tiles + fingerprint persisted
    # interlude: the scalar engine records a WIDE vote (no tile updates)
    r = ReferenceSlasher(E, store=ms)
    r.accept_attestation(_att([1], 2, 9, b"\x0b" * 32))
    r.process_queued(9)
    # back to columnar: tiles exist but are stale -> must rebuild
    rebuilds0 = REGISTRY.counter("slasher_span_rebuilds_total").value()
    c2 = ColumnarSlasher(E, store=ms)
    assert REGISTRY.counter("slasher_span_rebuilds_total").value() == rebuilds0 + 1
    c2.accept_attestation(_att([1], 3, 8, b"\x0c" * 32))  # surrounded by (2,9)
    assert c2.process_queued(9)["attester_slashings"] == 1
    # and a clean columnar restart (no interlude) does NOT rebuild
    c3 = ColumnarSlasher(E, store=ms)
    assert REGISTRY.counter("slasher_span_rebuilds_total").value() == rebuilds0 + 1
    del c3


def test_sparse_hostile_index_with_small_conflicts():
    """Regression (review): one huge sparse validator index in the cycle
    must not size the conflicted lookup table (guard on all_v, not just
    the conflicted set) — the cycle completes and matches the oracle."""
    huge = 2**40
    stream = [
        _att([5], 1, 4, b"\x0a" * 32),
        _att([5], 1, 4, b"\x0b" * 32),  # 5 is conflicted (double)
        _att([huge], 1, 4, b"\x0c" * 32),
    ]
    c, r = ColumnarSlasher(E), ReferenceSlasher(E)
    for a in stream:
        c.accept_attestation(a)
        r.accept_attestation(a)
    assert c.process_queued(5) == r.process_queued(5)
    assert _fingerprint(c) == _fingerprint(r)


def test_refused_submit_unclaims_epoch_not_inline():
    """Regression (review): a refused SLASHER_PROCESS submit must NOT run
    the cycle inline on the slot-tick caller — the epoch is unclaimed and
    the next tick retries."""
    from dataclasses import replace

    from lighthouse_tpu.beacon_chain.harness import BeaconChainHarness
    from lighthouse_tpu.beacon_processor import BeaconProcessor
    from lighthouse_tpu.crypto import bls
    from lighthouse_tpu.slasher.service import SlasherService
    from lighthouse_tpu.types.chain_spec import minimal_spec

    bls.set_backend("fake_crypto")
    spec = replace(minimal_spec(), altair_fork_epoch=0)
    h = BeaconChainHarness(spec, E, validator_count=16)
    svc = SlasherService(h.chain)
    ran = []
    orig = svc.slasher.process_queued
    svc.slasher.process_queued = lambda ep: (ran.append(ep), orig(ep))[1]
    svc.observe_indexed_attestation(_att([3], 0, 1, b"\x0a" * 32))
    svc.observe_indexed_attestation(_att([3], 0, 1, b"\x0b" * 32))

    class RefusingProc:
        def submit(self, *a, **kw):
            return False

    slot = 2 * E.SLOTS_PER_EPOCH
    assert svc.on_slot(slot, processor=RefusingProc()) is None
    assert not ran, "cycle ran inline on the slot-tick caller"
    # next tick, working processor: the unclaimed epoch is retried
    proc = BeaconProcessor(num_workers=1, name="network_beacon_processor")
    assert svc.on_slot(slot + 1, processor=proc) is None
    assert proc.drain(timeout=10)
    assert ran == [2], "epoch was not retried after the refused submit"
    assert h.chain.op_pool._attester_slashings
    proc.shutdown()


def test_expired_block_records_prune_at_unchanged_epoch():
    """Regression (review): pruning must run every cycle like the oracle
    — a block record below the slot floor expires even when no
    attestation epoch did, so a later conflicting header for the expired
    slot emits in NEITHER engine."""
    from lighthouse_tpu.slasher import SlasherConfig as _Cfg

    def header(proposer, slot, state_root):
        return T.SignedBeaconBlockHeader(
            message=T.BeaconBlockHeader(
                slot=slot,
                proposer_index=proposer,
                parent_root=b"\x11" * 32,
                state_root=state_root,
                body_root=b"\x22" * 32,
            ),
            signature=b"\x00" * 96,
        )

    for cls in (ColumnarSlasher, ReferenceSlasher):
        s = cls(E, _Cfg(history_length=4))
        s.process_queued(100)  # floor=96, slot_floor=768
        s.accept_block_header(header(1, 700, b"\xaa" * 32))
        s.process_queued(100)  # same epoch: slot-700 record must expire NOW
        assert 700 not in s._blocks.get(1, {}), cls.__name__
        s.accept_block_header(header(1, 700, b"\xbb" * 32))
        out = s.process_queued(100)
        assert out["proposer_slashings"] == 0, cls.__name__


@pytest.mark.parametrize("cls", [ColumnarSlasher, ReferenceSlasher])
def test_attestations_arriving_mid_cycle_are_not_dropped(cls):
    """Regression (review): appends racing a running cycle must survive
    into the next cycle (atomic queue swap, not iterate-then-clear)."""
    s = cls(E)
    late = [_att([5], 0, 3, b"\x0a" * 32), _att([5], 0, 3, b"\x0b" * 32)]
    orig_prune = s._prune

    def prune_and_race(epoch):
        # simulates a gossip thread appending while the cycle runs
        s._att_queue.extend(late)
        return orig_prune(epoch)

    s._prune = prune_and_race
    s.accept_attestation(_att([1], 0, 2, b"\x0c" * 32))
    s.process_queued(4)
    s._prune = orig_prune
    assert len(s._att_queue) == 2, "mid-cycle arrivals were dropped"
    out = s.process_queued(4)
    assert out["attester_slashings"] == 1  # the late double vote detected


def test_service_cycles_never_overlap():
    """Regression (review): the engines are not thread-safe — competing
    epoch claims may queue multiple cycles, but _process_epoch serializes
    them behind the run lock."""
    import time as _time
    from dataclasses import replace

    from lighthouse_tpu.beacon_chain.harness import BeaconChainHarness
    from lighthouse_tpu.beacon_processor import BeaconProcessor
    from lighthouse_tpu.crypto import bls
    from lighthouse_tpu.slasher.service import SlasherService
    from lighthouse_tpu.types.chain_spec import minimal_spec

    bls.set_backend("fake_crypto")
    spec = replace(minimal_spec(), altair_fork_epoch=0)
    h = BeaconChainHarness(spec, E, validator_count=16)
    svc = SlasherService(h.chain)
    active = []
    overlaps = []
    orig = svc.slasher.process_queued

    def slow_cycle(epoch):
        active.append(epoch)
        if len(active) > 1:
            overlaps.append(tuple(active))
        _time.sleep(0.05)
        out = orig(epoch)
        active.remove(epoch)
        return out

    svc.slasher.process_queued = slow_cycle
    proc = BeaconProcessor(num_workers=2, name="network_beacon_processor")
    # two distinct epochs claimed back-to-back: both queue, 2 workers
    svc.on_slot(2 * E.SLOTS_PER_EPOCH, processor=proc)
    svc.on_slot(3 * E.SLOTS_PER_EPOCH, processor=proc)
    assert proc.drain(timeout=10)
    assert not overlaps, f"cycles overlapped: {overlaps}"
    proc.shutdown()


def test_network_slot_tick_submits_slasher_cycle():
    """The PR 11 heartbeat slot tick drives detection through the
    network's own processor (the node path wiring)."""
    from dataclasses import replace

    from lighthouse_tpu.beacon_chain.harness import BeaconChainHarness
    from lighthouse_tpu.crypto import bls
    from lighthouse_tpu.network import NetworkService
    from lighthouse_tpu.slasher.service import SlasherService
    from lighthouse_tpu.types.chain_spec import minimal_spec

    bls.set_backend("fake_crypto")
    spec = replace(minimal_spec(), altair_fork_epoch=0)
    h = BeaconChainHarness(spec, E, validator_count=16)
    ns = NetworkService(
        h.chain, port=0, heartbeat_interval=None, sync_service_interval=None
    ).start()
    try:
        svc = SlasherService(h.chain)
        seen = []
        orig = svc.slasher.process_queued
        svc.slasher.process_queued = lambda ep: (
            seen.append(threading.current_thread().name),
            orig(ep),
        )[1]
        svc.observe_indexed_attestation(_att([3], 0, 1, b"\x0a" * 32))
        svc.observe_indexed_attestation(_att([3], 0, 1, b"\x0b" * 32))
        h.slot_clock.set_slot(2 * E.SLOTS_PER_EPOCH)
        ns.slot_tick()
        assert ns.processor.drain(timeout=10)
        assert seen and seen[0].startswith("network_beacon_processor")
        assert h.chain.op_pool._attester_slashings
    finally:
        ns.stop()
