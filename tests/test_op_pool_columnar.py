"""Columnar op-pool attestation indexing: bucket-per-data-root with
resident numpy masks + insert-time union, flat max-cover packing vs the
retained rescan reference, merge/dedup/cap behavior, get_aggregate, and
pruning over the bucket structure.

Contract (op_pool.py): `get_attestations_for_block` must return the
EXACT list the retained `get_attestations_for_block_reference` walk
returns — same attestations, same order — for any pool content and any
state, because both implement the same greedy max-cover (first maximal
gain in candidate order, per-data coverage, zero-gain stop)."""

import random
from dataclasses import replace

import numpy as np
import pytest

from lighthouse_tpu.beacon_chain.op_pool import OperationPool
from lighthouse_tpu.crypto import bls
from lighthouse_tpu.state_processing import interop_genesis_state
from lighthouse_tpu.state_processing.accessors import get_current_epoch
from lighthouse_tpu.types.chain_spec import minimal_spec
from lighthouse_tpu.types.containers import build_types
from lighthouse_tpu.types.eth_spec import MinimalEthSpec as E

T = build_types(E)


@pytest.fixture(scope="module")
def state():
    spec = replace(minimal_spec(), altair_fork_epoch=0)
    st = interop_genesis_state(
        bls.interop_keypairs(16), 1_600_000_000, b"\x42" * 32, spec, E
    )
    # deep enough that out-of-window and ancient-target fixtures have
    # room below slot 0 / epoch 0
    st.slot = 3 * E.SLOTS_PER_EPOCH + 2
    return st


def _att(state, slot, index, bits, target_epoch=None, source=None):
    current = get_current_epoch(state, E)
    return T.Attestation(
        aggregation_bits=bits,
        data=T.AttestationData(
            slot=slot,
            index=index,
            beacon_block_root=b"\x11" * 32,
            source=source if source is not None
            else state.current_justified_checkpoint,
            target=T.Checkpoint(
                epoch=current if target_epoch is None else target_epoch,
                root=b"\x22" * 32,
            ),
        ),
        signature=b"\x00" * 96,
    )


def _random_pool(state, rng, n_buckets=24, width=16):
    """A pool of randomized buckets: in-window and out-of-window slots,
    current/previous/ancient targets, wrong sources — the pack filters
    must agree bucket-wise with the reference's per-attestation checks."""
    pool = OperationPool(state_spec(state), E)
    current = get_current_epoch(state, E)
    for b in range(n_buckets):
        kind = rng.random()
        if kind < 0.6:
            slot = int(state.slot) - rng.randint(1, 6)  # in window
            target_epoch = None
            source = None
        elif kind < 0.75:
            slot = int(state.slot) - rng.randint(9, 12)  # outside window
            target_epoch = None
            source = None
        elif kind < 0.9:
            slot = int(state.slot) - rng.randint(1, 6)
            target_epoch = current - 2  # too-old target epoch
            source = None
        else:
            slot = int(state.slot) - rng.randint(1, 6)
            target_epoch = None
            source = T.Checkpoint(epoch=7, root=b"\x99" * 32)  # bad source
        for _ in range(rng.randint(1, 6)):
            bits = [rng.random() < 0.4 for _ in range(width)]
            if not any(bits):
                bits[rng.randrange(width)] = True
            pool._add_unmerged(
                _att(state, slot, b, bits, target_epoch, source)
            )
    return pool


def state_spec(state):
    return replace(minimal_spec(), altair_fork_epoch=0)


@pytest.mark.parametrize("seed", range(8))
def test_pack_differential_vs_reference(state, seed):
    rng = random.Random(seed)
    pool = _random_pool(state, rng)
    flat = pool.get_attestations_for_block(state)
    rescan = pool.get_attestations_for_block_reference(state)
    assert flat == rescan  # same objects, same order
    assert len(flat) <= E.MAX_ATTESTATIONS
    # a second pack is idempotent (packing is read-only)
    assert pool.get_attestations_for_block(state) == flat


def test_pack_respects_max_attestations(state):
    pool = OperationPool(state_spec(state), E)
    rng = random.Random(1)
    # more disjoint-singleton buckets than a block can carry
    for b in range(E.MAX_ATTESTATIONS + 8):
        bits = [i == (b % 16) for i in range(16)]
        pool._add_unmerged(_att(state, int(state.slot) - 1, b, bits))
    chosen = pool.get_attestations_for_block(state)
    assert len(chosen) == E.MAX_ATTESTATIONS
    assert chosen == pool.get_attestations_for_block_reference(state)


def test_insert_merges_first_disjoint_aggregate(state):
    """Greedy in-place aggregation: two disjoint patterns for the same
    data collapse into their union (one stored aggregate, signature
    aggregated), and the bucket's union mask tracks every insert."""
    kp = bls.interop_keypairs(2)
    pool = OperationPool(state_spec(state), E)
    half = [i < 8 for i in range(16)]
    other = [i >= 8 for i in range(16)]
    sig1 = kp[0].sk.sign(b"m1").to_bytes()
    sig2 = kp[1].sk.sign(b"m2").to_bytes()
    a1 = _att(state, int(state.slot) - 1, 0, half)
    a1 = T.Attestation(
        aggregation_bits=half, data=a1.data, signature=sig1
    )
    a2 = T.Attestation(
        aggregation_bits=other, data=a1.data, signature=sig2
    )
    pool.insert_attestation(a1)
    pool.insert_attestation(a2)
    assert pool.num_attestations() == 1
    merged = pool.get_aggregate(a1.data.hash_tree_root())
    assert list(merged.aggregation_bits) == [True] * 16
    (bucket,) = pool._attestations.values()
    assert bucket.union_mask.all()
    # exact duplicates are rejected without growing the bucket
    pool.insert_attestation(
        T.Attestation(
            aggregation_bits=[True] * 16, data=a1.data, signature=sig1
        )
    )
    assert pool.num_attestations() == 1


def test_merge_reproducing_existing_mask_dedupes(state):
    """A disjoint merge whose union equals an ALREADY-stored aggregate
    must replace that entry, not append a twin (the scalar dict's
    assignment dedup): bucket holds A=10, B=11; inserting C=01 merges
    with A into 11 == B -> exactly ONE stored aggregate remains."""
    kp = bls.interop_keypairs(3)
    pool = OperationPool(state_spec(state), E)
    base = _att(state, int(state.slot) - 1, 0, [True, False])
    def with_bits(bits, sk):
        return T.Attestation(
            aggregation_bits=bits, data=base.data,
            signature=sk.sign(b"x").to_bytes(),
        )
    pool.insert_attestation(with_bits([True, False], kp[0].sk))   # A=10
    pool._add_unmerged(with_bits([True, True], kp[1].sk))         # B=11
    assert pool.num_attestations() == 2
    pool.insert_attestation(with_bits([False, True], kp[2].sk))   # C=01
    assert pool.num_attestations() == 1
    (bucket,) = pool._attestations.values()
    assert [m.tolist() for m in bucket.masks] == [[True, True]]
    assert bucket.keys == {bucket.masks[0].tobytes()}
    # and an exact duplicate of the survivor is still rejected
    pool.insert_attestation(with_bits([True, True], kp[1].sk))
    assert pool.num_attestations() == 1


def test_insert_cap_bounds_bucket(state):
    pool = OperationPool(state_spec(state), E)
    # overlapping patterns (all share bit 0) never merge: the cap holds
    for j in range(OperationPool.MAX_AGGREGATES_PER_DATA + 8):
        bits = [True] + [i == j for i in range(40)]
        pool._add_unmerged(_att(state, int(state.slot) - 1, 0, bits))
    assert (
        pool.num_attestations() == OperationPool.MAX_AGGREGATES_PER_DATA
    )


def test_get_aggregate_prefers_highest_participation(state):
    pool = OperationPool(state_spec(state), E)
    small = [i < 2 for i in range(16)]
    big = [i < 9 for i in range(16)]
    a = _att(state, int(state.slot) - 1, 0, small)
    pool._add_unmerged(a)
    pool._add_unmerged(
        T.Attestation(
            aggregation_bits=big, data=a.data, signature=b"\x00" * 96
        )
    )
    got = pool.get_aggregate(a.data.hash_tree_root())
    assert list(got.aggregation_bits) == big
    assert pool.get_aggregate(b"\x77" * 32) is None


def test_prune_drops_stale_buckets(state):
    pool = OperationPool(state_spec(state), E)
    fresh = _att(state, int(state.slot) - 1, 0, [True] * 16)
    # two epochs back: below the previous-epoch retention line
    stale = _att(
        state, int(state.slot) - 2 * E.SLOTS_PER_EPOCH - 1, 1, [True] * 16
    )
    pool._add_unmerged(fresh)
    pool._add_unmerged(stale)
    assert pool.num_attestations() == 2
    pool.prune(state)
    assert pool.num_attestations() == 1
    assert pool.get_aggregate(fresh.data.hash_tree_root()) is not None
    assert pool.get_aggregate(stale.data.hash_tree_root()) is None


def test_empty_pool_and_all_filtered_pool_pack_empty(state):
    pool = OperationPool(state_spec(state), E)
    assert pool.get_attestations_for_block(state) == []
    pool._add_unmerged(
        _att(state, int(state.slot) - 10, 0, [True] * 16)  # out of window
    )
    assert pool.get_attestations_for_block(state) == []
    assert pool.get_attestations_for_block_reference(state) == []
