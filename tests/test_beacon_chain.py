"""BeaconChain orchestration tests: import pipeline, gossip verification,
attestation batch path, reorgs via fork choice, store persistence, pruning."""

import pytest

from lighthouse_tpu.beacon_chain import (
    AttestationError,
    BeaconChainHarness,
    BlockError,
)
from lighthouse_tpu.crypto import bls
from lighthouse_tpu.store import DBColumn, HotColdDB, MemoryStore, SqliteStore
from lighthouse_tpu.types import MinimalEthSpec, minimal_spec


@pytest.fixture(autouse=True)
def fake_crypto():
    bls.set_backend("fake_crypto")
    yield
    bls.set_backend("host")


@pytest.fixture
def harness():
    return BeaconChainHarness(minimal_spec(), MinimalEthSpec, validator_count=64)


def test_chain_finality(harness):
    harness.extend_chain(8 * 4)
    assert harness.justified_epoch == 3
    assert harness.finalized_epoch == 2
    assert harness.chain.head_state.slot == 32


def test_gossip_block_verification(harness):
    harness.extend_chain(2)
    chain = harness.chain
    slot = chain.head_state.slot + 1
    harness.slot_clock.set_slot(slot)
    import lighthouse_tpu.state_processing as sp

    state = chain.head_state.copy()
    while state.slot < slot:
        sp.per_slot_processing(state, harness.spec, harness.E)
    proposer = sp.get_beacon_proposer_index(state, harness.E)
    block, _ = chain.produce_block_on_state(
        slot, harness.randao_reveal(proposer, slot)
    )
    signed = harness.sign_block(block)
    gossip_verified = chain.verify_block_for_gossip(signed)
    # double-propose detection
    with pytest.raises(BlockError, match="already produced"):
        chain.verify_block_for_gossip(signed)
    chain.process_block(gossip_verified)
    assert chain.head_root == gossip_verified.block_root


def test_future_block_rejected(harness):
    harness.extend_chain(1)
    chain = harness.chain
    slot = chain.head_state.slot + 5
    import lighthouse_tpu.state_processing as sp

    state = chain.head_state.copy()
    while state.slot < slot:
        sp.per_slot_processing(state, harness.spec, harness.E)
    proposer = sp.get_beacon_proposer_index(state, harness.E)
    block, _ = chain.produce_block_on_state(
        slot, harness.randao_reveal(proposer, slot)
    )
    signed = harness.sign_block(block)
    # clock still at slot 1
    with pytest.raises(BlockError, match="future"):
        chain.verify_block_for_gossip(signed)


def test_unknown_parent_rejected(harness):
    harness.extend_chain(1)
    t = harness.chain.types
    orphan = t.SignedBeaconBlock(
        message=t.BeaconBlock(
            slot=2, proposer_index=0, parent_root=b"\x77" * 32
        )
    )
    with pytest.raises(BlockError, match="parent"):
        harness.chain.process_block(orphan)


def test_attestation_gossip_batch(harness):
    harness.extend_chain(2, attest=False)
    chain = harness.chain
    slot = chain.head_state.slot
    atts = harness.make_unaggregated_attestations(slot, chain.head_root)
    assert len(atts) == 8  # 64 validators / 8 slots per epoch
    results = chain.process_attestation_batch(atts)
    assert all(not isinstance(r, Exception) for r in results)
    # duplicates rejected by the observed-attesters cache
    results2 = chain.process_attestation_batch(atts)
    assert all(isinstance(r, AttestationError) for r in results2)


def test_attestation_unknown_block_rejected(harness):
    harness.extend_chain(1)
    atts = harness.make_unaggregated_attestations(1, harness.chain.head_root)
    t = harness.chain.types
    bad = t.Attestation(
        aggregation_bits=atts[0].aggregation_bits,
        data=t.AttestationData(
            slot=atts[0].data.slot,
            index=atts[0].data.index,
            beacon_block_root=b"\x55" * 32,  # unknown
            source=atts[0].data.source,
            target=atts[0].data.target,
        ),
        signature=atts[0].signature,
    )
    with pytest.raises(AttestationError, match="unknown"):
        harness.chain.process_attestation(bad)


def test_reorg_by_weight(harness):
    """Two competing forks; attestations drive the head to the heavier one."""
    harness.extend_chain(2, attest=False)
    chain = harness.chain
    common_root = chain.head_root
    slot_a = chain.head_state.slot + 1
    harness.slot_clock.set_slot(slot_a + 1)

    # fork A at slot_a
    import lighthouse_tpu.state_processing as sp

    state = chain.head_state.copy()
    while state.slot < slot_a:
        sp.per_slot_processing(state, harness.spec, harness.E)
    proposer = sp.get_beacon_proposer_index(state, harness.E)
    block_a, _ = chain.produce_block_on_state(
        slot_a, harness.randao_reveal(proposer, slot_a)
    )
    signed_a = harness.sign_block(block_a)
    root_a = chain.process_block(signed_a)

    # fork B: different graffiti at the same slot (same proposer)
    block_b, _ = chain_produce_on(
        chain, common_root, slot_a, harness, graffiti=b"\x01" * 32
    )
    signed_b = harness.sign_block(block_b)
    root_b = chain.process_block(signed_b)
    assert root_a != root_b

    # all validators attest to fork B
    atts = harness.make_unaggregated_attestations(slot_a, root_b)
    chain.process_attestation_batch(atts)
    head = chain.recompute_head()
    assert head == root_b


def chain_produce_on(chain, parent_root, slot, harness, graffiti):
    """Produce a block on an explicit parent (not the current head)."""
    import lighthouse_tpu.state_processing as sp

    state = chain.state_at_block_root(parent_root).copy()
    while state.slot < slot:
        sp.per_slot_processing(state, harness.spec, harness.E)
    proposer = sp.get_beacon_proposer_index(state, harness.E)
    body = chain.types.BeaconBlockBody(
        randao_reveal=harness.randao_reveal(proposer, slot),
        eth1_data=state.eth1_data,
        graffiti=graffiti,
    )
    block = chain.types.BeaconBlock(
        slot=slot,
        proposer_index=proposer,
        parent_root=parent_root,
        state_root=b"\x00" * 32,
        body=body,
    )
    post = state.copy()
    ctxt = sp.ConsensusContext(slot)
    ctxt.set_proposer_index(proposer)
    sp.per_block_processing(
        post,
        chain.types.SignedBeaconBlock(message=block),
        harness.spec,
        harness.E,
        strategy=sp.BlockSignatureStrategy.NO_VERIFICATION,
        ctxt=ctxt,
        verify_block_root=False,
    )
    block.state_root = post.hash_tree_root()
    return block, post


def test_store_roundtrip(harness):
    harness.extend_chain(3)
    chain = harness.chain
    head_block = chain.head_block()
    stored = chain.store.get_block(chain.head_root)
    assert stored.hash_tree_root() == head_block.hash_tree_root()
    state = chain.store.get_state(head_block.message.state_root)
    assert state.slot == chain.head_state.slot
    assert state.hash_tree_root() == chain.head_state.hash_tree_root()


def test_sqlite_store(tmp_path):
    store = SqliteStore(str(tmp_path / "chain.db"))
    store.put(DBColumn.BEACON_BLOCK, b"k1", b"v1")
    store.do_atomically(
        [
            ("put", DBColumn.BEACON_STATE, b"k2", b"v2"),
            ("put", DBColumn.BEACON_BLOCK, b"k3", b"v3"),
            ("delete", DBColumn.BEACON_BLOCK, b"k1"),
        ]
    )
    assert store.get(DBColumn.BEACON_BLOCK, b"k1") is None
    assert store.get(DBColumn.BEACON_STATE, b"k2") == b"v2"
    assert store.get(DBColumn.BEACON_BLOCK, b"k3") == b"v3"
    assert store.keys(DBColumn.BEACON_BLOCK) == [b"k3"]
    store.close()


def test_chain_on_sqlite(tmp_path):
    store = HotColdDB(SqliteStore(str(tmp_path / "hot.db")))
    h = BeaconChainHarness(
        minimal_spec(), MinimalEthSpec, validator_count=64, store=store
    )
    h.extend_chain(8)
    assert h.chain.store.get_block(h.chain.head_root) is not None


def test_finality_prunes_states(harness):
    harness.extend_chain(8 * 5)
    finalized_epoch = harness.finalized_epoch
    assert finalized_epoch >= 3
    # snapshot cache only keeps unfinalized states (+ finalized root)
    finalized_slot = finalized_epoch * 8
    old = [
        r
        for r, s in harness.chain._states.items()
        if s.slot < finalized_slot and r != harness.chain.fork_choice.store.finalized_checkpoint.root
    ]
    assert old == []
    # finalized blocks were migrated to cold
    assert harness.chain.store.split_slot == finalized_slot
