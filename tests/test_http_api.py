"""HTTP Beacon API over a live harness chain (http_api test_utils analog):
a real threaded server, exercised with urllib — node status, state/block
queries, duties, SSZ block round-trip publishing, and /metrics."""

import json
import urllib.request
from dataclasses import replace

import pytest

from lighthouse_tpu.beacon_chain.harness import BeaconChainHarness
from lighthouse_tpu.crypto import bls
from lighthouse_tpu.http_api import HttpApiServer
from lighthouse_tpu.types.chain_spec import minimal_spec
from lighthouse_tpu.types.eth_spec import MinimalEthSpec as E


@pytest.fixture(scope="module")
def rig():
    bls.set_backend("fake_crypto")
    spec = replace(minimal_spec(), altair_fork_epoch=0)
    h = BeaconChainHarness(spec, E, validator_count=16)
    h.extend_chain(E.SLOTS_PER_EPOCH + 2)
    server = HttpApiServer(h.chain).start()
    yield h, server
    server.stop()


def _get(server, path, accept=None):
    req = urllib.request.Request(f"http://127.0.0.1:{server.port}{path}")
    if accept:
        req.add_header("Accept", accept)
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            data = resp.read()
            ctype = resp.headers.get("Content-Type", "")
            return resp.status, (json.loads(data) if "json" in ctype else data)
    except urllib.error.HTTPError as e:
        data = e.read()
        try:
            return e.code, json.loads(data)
        except ValueError:
            return e.code, data


def test_node_endpoints(rig):
    h, server = rig
    status, _ = _get(server, "/eth/v1/node/health")
    assert status == 200
    _, version = _get(server, "/eth/v1/node/version")
    assert "lighthouse-tpu" in version["data"]["version"]
    _, syncing = _get(server, "/eth/v1/node/syncing")
    assert syncing["data"]["head_slot"] == str(h.chain.head_state.slot)


def test_genesis_and_state_endpoints(rig):
    h, server = rig
    _, genesis = _get(server, "/eth/v1/beacon/genesis")
    assert genesis["data"]["genesis_validators_root"] == "0x" + (
        h.chain.genesis_validators_root.hex()
    )
    _, root = _get(server, "/eth/v1/beacon/states/head/root")
    assert root["data"]["root"] == "0x" + h.chain.head_state.hash_tree_root().hex()
    _, fork = _get(server, "/eth/v1/beacon/states/head/fork")
    assert fork["data"]["current_version"] == "0x" + (
        h.chain.head_state.fork.current_version.hex()
    )
    _, fin = _get(server, "/eth/v1/beacon/states/head/finality_checkpoints")
    assert int(fin["data"]["current_justified"]["epoch"]) >= 0
    _, vals = _get(server, "/eth/v1/beacon/states/head/validators?id=0,2")
    assert len(vals["data"]) == 2
    assert vals["data"][0]["validator"]["pubkey"].startswith("0x")


def test_block_endpoints_and_ssz(rig):
    h, server = rig
    _, header = _get(server, "/eth/v1/beacon/headers/head")
    assert header["data"]["root"] == "0x" + h.chain.head_root.hex()
    status, ssz = _get(
        server, "/eth/v2/beacon/blocks/head", accept="application/octet-stream"
    )
    assert status == 200
    assert ssz == h.chain.head_block().serialize()
    _, root = _get(server, f"/eth/v1/beacon/blocks/{h.chain.head_state.slot}/root")
    assert root["data"]["root"] == "0x" + h.chain.head_root.hex()
    status, err = _get(server, "/eth/v1/beacon/headers/0x" + "00" * 32)
    assert err["code"] == 404


def test_proposer_duties(rig):
    h, server = rig
    epoch = h.chain.head_state.slot // E.SLOTS_PER_EPOCH
    _, duties = _get(server, f"/eth/v1/validator/duties/proposer/{epoch}")
    assert len(duties["data"]) == E.SLOTS_PER_EPOCH
    assert all(d["pubkey"].startswith("0x") for d in duties["data"])


def test_publish_block_ssz_roundtrip(rig):
    h, server = rig
    slot = h.chain.head_state.slot + 1
    h.slot_clock.set_slot(slot)
    # produce+sign but publish via the API instead of process_block
    state = h.chain.head_state
    from lighthouse_tpu.state_processing import per_slot_processing
    from lighthouse_tpu.state_processing.accessors import get_beacon_proposer_index

    proposer_state = state.copy()
    while proposer_state.slot < slot:
        per_slot_processing(proposer_state, h.spec, E)
    proposer = get_beacon_proposer_index(proposer_state, E)
    parent_root = h.chain.head_root
    block, _post = h.chain.produce_block_on_state(
        slot,
        h.randao_reveal(proposer, slot, proposer_state),
        sync_aggregate_fn=lambda st: h.make_sync_aggregate(st, slot, parent_root),
    )
    signed = h.sign_block(block, proposer_state)
    data = signed.serialize()
    req = urllib.request.Request(
        f"http://127.0.0.1:{server.port}/eth/v1/beacon/blocks",
        data=data,
        headers={"Content-Type": "application/octet-stream"},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=30) as resp:
        assert resp.status == 200
    assert h.chain.head_state.slot == slot  # imported through the API


def test_metrics_endpoint(rig):
    _h, server = rig
    status, body = _get(server, "/metrics")
    assert status == 200
    assert b"beacon_blocks_imported_total" in body


def _post_json(server, path, obj):
    body = json.dumps(obj).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{server.port}{path}",
        data=body,
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=10) as resp:
        return json.loads(resp.read())


def test_config_routes(rig):
    h, server = rig
    spec_doc = _get(server, "/eth/v1/config/spec")[1]["data"]
    assert spec_doc["SECONDS_PER_SLOT"] == str(h.spec.seconds_per_slot)
    dc = _get(server, "/eth/v1/config/deposit_contract")[1]["data"]
    assert dc["address"].startswith("0x") and len(dc["address"]) == 42
    sched = _get(server, "/eth/v1/config/fork_schedule")[1]["data"]
    assert sched[0]["epoch"] == "0"
    assert any(f["current_version"] != sched[0]["current_version"] for f in sched)


def test_committees_and_duty_routes(rig):
    h, server = rig
    epoch = h.chain.head_state.slot // E.SLOTS_PER_EPOCH
    comm = _get(
        server, f"/eth/v1/beacon/states/head/committees?epoch={epoch}"
    )[1]["data"]
    assert len(comm) >= E.SLOTS_PER_EPOCH  # >=1 committee per slot
    all_vals = sorted(int(v) for c in comm for v in c["validators"])
    assert all_vals == list(range(16))  # every validator seated once

    duties = _post_json(
        server, f"/eth/v1/validator/duties/attester/{epoch}", ["0", "5"]
    )["data"]
    assert sorted(int(d["validator_index"]) for d in duties) == [0, 5]
    d0 = duties[0]
    assert int(d0["committee_length"]) >= 1 and "slot" in d0

    sync = _post_json(
        server, f"/eth/v1/validator/duties/sync/{epoch}", list(range(16))
    )["data"]
    # altair-at-genesis: every committee position maps to our validators
    positions = [p for d in sync for p in d["validator_sync_committee_indices"]]
    assert len(positions) == E.SYNC_COMMITTEE_SIZE


def test_pool_and_blob_routes(rig):
    h, server = rig
    slot = h.chain.head_state.slot
    h.attest_to_head(slot)
    pool = _get(server, "/eth/v1/beacon/pool/attestations")[1]["data"]
    assert pool and pool[0]["signature"].startswith("0x")
    _code, exits = _get(server, "/eth/v1/beacon/pool/voluntary_exits")
    assert exits["data"] == []
    # blob route: empty SSZ list for a blobless block
    code, doc = _get(server, "/eth/v1/beacon/blob_sidecars/head")
    assert code == 200 and doc["data"] == []
    code, raw = _get(
        server, "/eth/v1/beacon/blob_sidecars/head",
        accept="application/octet-stream",
    )
    assert code == 200 and raw == b""


def test_node_identity_and_peers_routes():
    """node/identity + node/peers read the attached NetworkService."""
    from lighthouse_tpu.network import NetworkService

    bls.set_backend("fake_crypto")
    spec = replace(minimal_spec(), altair_fork_epoch=0)
    a = BeaconChainHarness(spec, E, validator_count=8)
    b = BeaconChainHarness(spec, E, validator_count=8)
    na = NetworkService(a.chain).start()
    nb = NetworkService(b.chain).start()
    srv = HttpApiServer(a.chain, network=na).start()
    try:
        nb.connect("127.0.0.1", na.port)
        import time as _t

        _t.sleep(0.2)
        _code, ident = _get(srv, "/eth/v1/node/identity")
        assert ident["data"]["p2p_addresses"] == [
            f"/ip4/127.0.0.1/tcp/{na.port}"
        ]
        _code, peers = _get(srv, "/eth/v1/node/peers")
        assert peers["meta"]["count"] == 1
        assert peers["data"][0]["state"] == "connected"
    finally:
        srv.stop()
        na.stop()
        nb.stop()


def test_validator_balances_and_single_validator(rig):
    h, server = rig
    _, balances = _get(server, "/eth/v1/beacon/states/head/validator_balances")
    assert len(balances["data"]) == 16
    assert int(balances["data"][0]["balance"]) > 0
    _, filtered = _get(
        server, "/eth/v1/beacon/states/head/validator_balances?id=2,3"
    )
    assert [e["index"] for e in filtered["data"]] == ["2", "3"]

    _, one = _get(server, "/eth/v1/beacon/states/head/validators/5")
    assert one["data"]["index"] == "5"
    pubkey = one["data"]["validator"]["pubkey"]
    _, by_pk = _get(server, f"/eth/v1/beacon/states/head/validators/{pubkey}")
    assert by_pk["data"]["index"] == "5"
    status, _ = _get(server, "/eth/v1/beacon/states/head/validators/9999")
    assert status == 404


def test_randao_and_peer_count(rig):
    h, server = rig
    _, randao = _get(server, "/eth/v1/beacon/states/head/randao")
    assert randao["data"]["randao"].startswith("0x")
    assert len(randao["data"]["randao"]) == 66
    _, pc = _get(server, "/eth/v1/node/peer_count")
    assert pc["data"]["connected"] == "0"  # no network wired in this rig


def test_block_rewards_route(rig):
    """Per-component proposer rewards: the replayed attestation+sync
    rewards must equal the actual proposer balance credit."""
    h, server = rig
    head = h.chain.head_block()
    _, rewards = _get(
        server, f"/eth/v1/beacon/rewards/blocks/{head.message.slot}"
    )
    data = rewards["data"]
    proposer = int(head.message.proposer_index)
    assert data["proposer_index"] == str(proposer)
    # ground truth: the proposer's ACTUAL balance credit across the block
    # (pre-state advanced to the block slot vs the stored post-state)
    from lighthouse_tpu.state_processing import per_slot_processing

    pre = h.chain.state_for_block_root(bytes(head.message.parent_root)).copy()
    while pre.slot < head.message.slot:
        per_slot_processing(pre, h.chain.spec, E)
    post = h.chain.state_for_block_root(h.chain.head_root)
    actual_delta = int(post.balances[proposer]) - int(pre.balances[proposer])
    assert int(data["total"]) == actual_delta
    # a full block of attestations earns a positive proposer reward
    assert int(data["attestations"]) > 0


def test_slashing_pool_routes(rig):
    h, server = rig
    _, ps = _get(server, "/eth/v1/beacon/pool/proposer_slashings")
    _, atts = _get(server, "/eth/v1/beacon/pool/attester_slashings")
    assert ps["data"] == [] and atts["data"] == []
    # publish a real proposer slashing (two signed headers, same slot)
    from lighthouse_tpu.types.chain_spec import Domain, compute_signing_root

    t = h.chain.types
    state = h.chain.head_state
    slot = int(state.slot)
    proposer = int(h.chain.head_block().message.proposer_index)

    def header(state_root):
        return t.BeaconBlockHeader(
            slot=slot,
            proposer_index=proposer,
            parent_root=b"\x11" * 32,
            state_root=state_root,
            body_root=b"\x22" * 32,
        )

    def sign(msg):
        domain = h.chain.spec.get_domain(
            slot // E.SLOTS_PER_EPOCH,
            Domain.BEACON_PROPOSER,
            state.fork,
            h.chain.genesis_validators_root,
        )
        root = compute_signing_root(msg.hash_tree_root(), domain)
        return h.keypairs[proposer].sk.sign(root).to_bytes()

    h1, h2 = header(b"\x01" * 32), header(b"\x02" * 32)
    slashing = t.ProposerSlashing(
        signed_header_1=t.SignedBeaconBlockHeader(message=h1, signature=sign(h1)),
        signed_header_2=t.SignedBeaconBlockHeader(message=h2, signature=sign(h2)),
    )
    req = urllib.request.Request(
        f"http://127.0.0.1:{server.port}/eth/v1/beacon/pool/proposer_slashings",
        data=slashing.serialize(),
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=10) as resp:
        assert resp.status == 200
    _, ps = _get(server, "/eth/v1/beacon/pool/proposer_slashings")
    assert len(ps["data"]) == 1
    assert ps["data"][0]["signed_header_1"]["message"]["proposer_index"] == str(proposer)


def test_sync_committee_rewards_route(rig):
    """Per-validator sync rewards sum to the block's sync_aggregate
    proposer-side component's participant pool; absent members go
    negative (spec process_sync_aggregate semantics)."""
    h, server = rig
    head = h.chain.head_block()
    req = urllib.request.Request(
        f"http://127.0.0.1:{server.port}"
        f"/eth/v1/beacon/rewards/sync_committee/{head.message.slot}",
        data=b"[]",
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=10) as resp:
        data = json.loads(resp.read())["data"]
    assert data, "minimal preset always has a sync committee"
    bits = list(head.message.body.sync_aggregate.sync_committee_bits)
    # ground truth: replayed per-validator deltas equal the actual
    # balance movement attributable to the sync aggregate — every entry's
    # validator is a committee member, rewards positive iff any set bit
    rewards = {int(e["validator_index"]): int(e["reward"]) for e in data}
    assert any(v > 0 for v in rewards.values()) == any(bits)
    # filtered query returns only the requested validator
    some_idx = next(iter(rewards))
    req = urllib.request.Request(
        f"http://127.0.0.1:{server.port}"
        f"/eth/v1/beacon/rewards/sync_committee/{head.message.slot}",
        data=json.dumps([str(some_idx)]).encode(),
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=10) as resp:
        filtered = json.loads(resp.read())["data"]
    assert [int(e["validator_index"]) for e in filtered] == [some_idx]


def test_attestation_rewards_route(rig):
    """Per-validator flag deltas must sum exactly to what the real epoch
    transition's rewards-and-penalties step applies."""
    h, server = rig
    # the requested epoch's rewards need the canonical state at the END
    # of epoch+1 — extend so epoch head//SPE - 2 is fully computable
    h.extend_chain(E.SLOTS_PER_EPOCH)
    epoch = int(h.chain.head_state.slot) // E.SLOTS_PER_EPOCH - 2
    assert epoch >= 0
    req = urllib.request.Request(
        f"http://127.0.0.1:{server.port}"
        f"/eth/v1/beacon/rewards/attestations/{epoch}",
        data=b"[]",
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=10) as resp:
        data = json.loads(resp.read())["data"]
    totals = {
        int(e["validator_index"]): (
            int(e["head"]) + int(e["target"]) + int(e["source"])
            + int(e["inactivity"])
        )
        for e in data["total_rewards"]
    }
    assert totals, "eligible validators expected"
    assert data["ideal_rewards"], "at least one effective-balance tier"

    # ground truth: the actual balance deltas the transition applies
    from lighthouse_tpu.state_processing import per_slot_processing
    from lighthouse_tpu.state_processing.altair import (
        process_rewards_and_penalties_altair,
    )
    from lighthouse_tpu.types.chain_spec import ForkName

    target_slot = (epoch + 2) * E.SLOTS_PER_EPOCH - 1
    anc = h.chain.fork_choice.proto.proto_array.ancestor_at_slot(
        h.chain.head_root, target_slot
    )
    st = h.chain.state_for_block_root(anc).copy()
    while st.slot < target_slot:
        per_slot_processing(st, h.chain.spec, E)
    before = [int(b) for b in st.balances]
    process_rewards_and_penalties_altair(st, h.chain.spec, E, ForkName.ALTAIR)
    after = [int(b) for b in st.balances]
    for i, delta in totals.items():
        assert after[i] - before[i] == delta, f"validator {i}"
    # the harness chain mostly attests: most validators earn net rewards
    # (earlier module tests leave a few unattested slots, so not ALL)
    assert sum(1 for d in totals.values() if d > 0) > len(totals) // 2
