"""BLS12-381 curve + signature scheme tests.

Oracles: published generator encodings, the reference's interop-keypair
golden vectors (common/eth2_interop_keypairs/specs/), RFC 9380
expand_message_xmd vectors, and algebraic self-consistency (bilinearity,
homomorphism, subgroup orders).
"""

import hashlib
import random
import re

import pytest

from lighthouse_tpu.crypto import bls
from lighthouse_tpu.crypto.bls12_381 import (
    FQ,
    FQ2,
    G1_GEN,
    G2_GEN,
    P,
    R,
    g1_from_bytes,
    g1_in_subgroup,
    g1_to_bytes,
    g2_from_bytes,
    g2_in_subgroup,
    g2_to_bytes,
    hash_to_g2,
    inf,
    is_inf,
    pairing,
    pairing_check,
    pt_add,
    pt_eq,
    pt_mul,
    pt_neg,
)
from lighthouse_tpu.crypto.bls12_381 import fields as F
from lighthouse_tpu.crypto.bls12_381.hash_to_curve import expand_message_xmd


@pytest.fixture(autouse=True)
def host_backend():
    bls.set_backend("host")
    yield
    bls.set_backend("host")


def test_generators_valid():
    assert g1_in_subgroup(G1_GEN)
    assert g2_in_subgroup(G2_GEN)
    assert is_inf(FQ, pt_mul(FQ, G1_GEN, R))
    assert is_inf(FQ2, pt_mul(FQ2, G2_GEN, R))


def test_known_generator_encodings():
    assert g1_to_bytes(G1_GEN).hex() == (
        "97f1d3a73197d7942695638c4fa9ac0fc3688c4f9774b905a14e3a3f171bac58"
        "6c55e83ff97a1aeffb3af00adb22c6bb"
    )
    assert g2_to_bytes(G2_GEN).hex() == (
        "93e02b6052719f607dacd3a088274f65596bd0d09920b61ab5da61bbdc7f5049"
        "334cf11213945d57e5ac7d055d042b7e024aa2b2f08f0a91260805272dc51051"
        "c6e47ad4fa403b02b4510b647ae3d1770bac0326a805bbefd48056c8c121bdb8"
    )


def test_point_serialization_roundtrip():
    rng = random.Random(0)
    for _ in range(4):
        k = rng.randrange(1, R)
        p1 = pt_mul(FQ, G1_GEN, k)
        assert pt_eq(FQ, g1_from_bytes(g1_to_bytes(p1)), p1)
        p2 = pt_mul(FQ2, G2_GEN, k)
        assert pt_eq(FQ2, g2_from_bytes(g2_to_bytes(p2)), p2)
    assert is_inf(FQ, g1_from_bytes(g1_to_bytes(inf(FQ))))
    assert is_inf(FQ2, g2_from_bytes(g2_to_bytes(inf(FQ2))))


def test_deserialize_rejects_bad_points():
    # find an x with no curve point (rhs non-square)
    x = 1
    while pow((x * x * x + 4) % P, (P - 1) // 2, P) == 1:
        x += 1
    data = bytearray(x.to_bytes(48, "big"))
    data[0] |= 0x80
    with pytest.raises(ValueError):
        g1_from_bytes(bytes(data))
    with pytest.raises(ValueError):
        g1_from_bytes(b"\x00" * 48)  # compression bit missing
    with pytest.raises(ValueError):
        g1_from_bytes(bytes([0xC0, 1]) + bytes(46))  # infinity with junk
    with pytest.raises(ValueError):
        g1_from_bytes(bytes([0x80]) + b"\xff" * 47)  # x >= p


def test_pairing_bilinear():
    e = pairing(G1_GEN, G2_GEN)
    assert e != F.F12_ONE
    assert F.f12_pow(e, R) == F.F12_ONE
    a, b = 5, 9
    lhs = pairing(pt_mul(FQ, G1_GEN, a), pt_mul(FQ2, G2_GEN, b))
    assert lhs == F.f12_pow(e, a * b)
    assert pairing_check([(G1_GEN, G2_GEN), (pt_neg(FQ, G1_GEN), G2_GEN)])


def test_expand_message_xmd_rfc9380_vectors():
    dst = b"QUUX-V01-CS02-with-expander-SHA256-128"
    assert expand_message_xmd(b"", dst, 0x20).hex() == (
        "68a985b87eb6b46952128911f2a4412bbc302a9d759667f87f7a21d803f07235"
    )
    assert expand_message_xmd(b"abc", dst, 0x20).hex() == (
        "d8ccab23b5985ccea865c6c97b6e5b8350e794e603b4b97902f53a8a0d605615"
    )


def test_hash_to_g2_properties():
    h = hash_to_g2(b"\x11" * 32)
    assert g2_in_subgroup(h)
    assert not is_inf(FQ2, h)
    assert pt_eq(FQ2, h, hash_to_g2(b"\x11" * 32))
    assert not pt_eq(FQ2, h, hash_to_g2(b"\x22" * 32))


def test_interop_keypairs_match_published_vectors():
    """Validators 0 and 1 of the eth2 interop mocked-start keygen spec
    (ethereum/eth2.0-pm interop/mocked_start), hand-transcribed — the
    canonical keys every client's interop docs quote. Independent of both
    this repo's derivation code and the reference checkout."""
    vectors = [
        (
            "25295f0d1d592a90b333e26e85149708208e9f8e8bc18f6c77bd62f8ad7a6866",
            "a99a76ed7796f7be22d5b7e85deeb7c5677e88e511e0b337618f8c4eb61349b4"
            "bf2d153f649f7b53359fe8b94a38e44c",
        ),
        (
            "51d0b65185db6989ab0b560d6deed19c7ead0e24b9b6372cbecb1f26bdfad000",
            "b89bebc699769726a318c8e9971bd3171297c61aea4a6578a7a4f94b547dcba5"
            "bac16a89108b6b6a1fe3695d1a874a0b",
        ),
    ]
    kps = bls.interop_keypairs(len(vectors))
    for i, (sk_hex, pk_hex) in enumerate(vectors):
        assert kps[i].sk.scalar == int(sk_hex, 16)
        assert kps[i].pk.to_bytes().hex() == pk_hex


def test_interop_keypairs_match_reference_golden_vectors():
    """Full 10-validator sweep against the reference checkout's yaml —
    only runnable where /root/reference is mounted."""
    path = (
        "/root/reference/common/eth2_interop_keypairs/specs/"
        "keygen_10_validators.yaml"
    )
    import os

    if not os.path.exists(path):
        pytest.skip("reference checkout not mounted in this environment")
    text = open(path).read()
    pairs = re.findall(
        r"privkey: '0x([0-9a-f]+)',\s*\n\s*pubkey: '0x([0-9a-f]+)'", text
    )
    assert len(pairs) == 10
    for i, (sk_hex, pk_hex) in enumerate(pairs):
        kp = bls.interop_keypairs(i + 1)[i]
        assert kp.sk.scalar == int(sk_hex, 16)
        assert kp.pk.to_bytes().hex() == pk_hex


def test_sign_verify():
    sk = bls.interop_secret_key(0)
    pk = sk.public_key()
    msg = hashlib.sha256(b"test message").digest()
    sig = sk.sign(msg)
    assert sig.verify(pk, msg)
    assert not sig.verify(pk, hashlib.sha256(b"other").digest())
    other_pk = bls.interop_secret_key(1).public_key()
    assert not sig.verify(other_pk, msg)


def test_infinity_signature_rejected():
    pk = bls.interop_secret_key(0).public_key()
    sig = bls.Signature(bls.INFINITY_SIGNATURE)
    assert not sig.verify(pk, b"\x00" * 32)


def test_aggregate_signature():
    msg = hashlib.sha256(b"aggregate me").digest()
    kps = bls.interop_keypairs(4)
    agg = bls.AggregateSignature.from_signatures([kp.sk.sign(msg) for kp in kps])
    assert agg.fast_aggregate_verify([kp.pk for kp in kps], msg)
    assert not agg.fast_aggregate_verify([kp.pk for kp in kps[:3]], msg)


def test_verify_signature_sets_batch():
    kps = bls.interop_keypairs(5)
    sets = []
    for i, kp in enumerate(kps):
        msg = hashlib.sha256(f"msg{i % 2}".encode()).digest()  # shared messages
        sets.append(bls.SignatureSet.single(kp.sk.sign(msg), kp.pk, msg))
    rng = random.Random(1234)
    assert bls.verify_signature_sets(sets, rng)
    # tamper one signature
    bad = list(sets)
    bad[2] = bls.SignatureSet.single(sets[3].signature, sets[2].pubkeys[0], sets[2].message)
    assert not bls.verify_signature_sets(bad, random.Random(99))
    # multi-pubkey set (aggregate attestation shape)
    msg = hashlib.sha256(b"committee").digest()
    agg = bls.AggregateSignature.from_signatures([kp.sk.sign(msg) for kp in kps])
    sets.append(
        bls.SignatureSet(
            signature=agg.to_signature(),
            pubkeys=[kp.pk for kp in kps],
            message=msg,
        )
    )
    assert bls.verify_signature_sets(sets, random.Random(7))


def test_fake_crypto_backend():
    bls.set_backend("fake_crypto")
    sk = bls.interop_secret_key(3)
    sig = sk.sign(b"\x01" * 32)
    assert len(sig.to_bytes()) == 96
    assert sig.verify(sk.public_key(), b"\x01" * 32)
    assert bls.verify_signature_sets(
        [bls.SignatureSet.single(sig, sk.public_key(), b"\x02" * 32)]
    )
    # deterministic
    assert sk.sign(b"\x01" * 32) == sig


def test_secret_key_roundtrip():
    sk = bls.SecretKey.random()
    assert bls.SecretKey.from_bytes(sk.to_bytes()).scalar == sk.scalar
    with pytest.raises(bls.BlsError):
        bls.SecretKey(0)
    with pytest.raises(bls.BlsError):
        bls.SecretKey(R)
