"""Stream multiplexing (network/mux.py + RpcClient mux mode).

Unit: many concurrent logical streams over one socketpair, interleaved
frames, FIN/RST semantics, reader-death EOF. Integration: a mux-mode
RpcClient reuses ONE connection (and one Noise handshake) across many
requests against a live node, with the full stack also running muxed
over noise."""

import socket
import threading
import time
from dataclasses import replace

import pytest

# the noise-over-mux integration tests need the optional `cryptography`
# package (see network/noise.py's lazy import guard)
pytest.importorskip("cryptography")

from lighthouse_tpu.beacon_chain.harness import BeaconChainHarness
from lighthouse_tpu.crypto import bls
from lighthouse_tpu.network import NetworkService
from lighthouse_tpu.network.mux import MuxedConnection, MuxError
from lighthouse_tpu.network.noise import NoiseTransport
from lighthouse_tpu.network.rpc import RpcClient
from lighthouse_tpu.types.chain_spec import minimal_spec
from lighthouse_tpu.types.eth_spec import MinimalEthSpec as E


def _conn_pair():
    sa, sb = socket.socketpair()
    client = MuxedConnection(sa, initiator=True)
    server = MuxedConnection(sb, initiator=False)
    return client, server


def test_many_streams_interleaved():
    client, server = _conn_pair()
    streams = [client.open_stream() for _ in range(8)]
    # interleave writes across all streams
    for rnd in range(5):
        for i, s in enumerate(streams):
            s.sendall(bytes([i]) * (rnd + 1))
    got = {}
    for _ in range(8):
        s = server.accept(timeout=5)
        assert s is not None
        got[s.stream_id] = s
    # initiator ids are odd (yamux convention)
    assert all(sid % 2 == 1 for sid in got)
    for i, s in enumerate(streams):
        srv = got[s.stream_id]
        data = bytearray()
        while len(data) < 1 + 2 + 3 + 4 + 5:
            data += srv.recv(64)
        assert bytes(data) == bytes([i]) * 15
    # echo back on one stream
    got[streams[3].stream_id].sendall(b"echo")
    assert streams[3].recv(16) == b"echo"
    client.close()
    server.close()


def test_fin_gives_eof_and_reset_raises():
    client, server = _conn_pair()
    s = client.open_stream()
    s.sendall(b"payload")
    srv = server.accept(timeout=5)
    assert srv.recv(64) == b"payload"
    s.shutdown(socket.SHUT_WR)  # FIN
    assert srv.recv(64) == b""  # clean EOF
    # RST on another stream surfaces as an error
    s2 = client.open_stream()
    srv2 = server.accept(timeout=5)
    from lighthouse_tpu.network.mux import FLAG_RST

    client.send_frame(s2.stream_id, FLAG_RST, b"")
    with pytest.raises(MuxError):
        srv2.settimeout(5)
        srv2.recv(1)
    client.close()
    server.close()


def test_connection_death_eofs_all_streams():
    client, server = _conn_pair()
    s1, s2 = client.open_stream(), client.open_stream()
    server.close()  # underlying socket dies
    time.sleep(0.2)
    s1.settimeout(2)
    s2.settimeout(2)
    assert s1.recv(1) == b""
    assert s2.recv(1) == b""
    assert not client.alive


def test_big_transfer_spans_frames():
    client, server = _conn_pair()
    s = client.open_stream()
    big = b"ABCD" * 100_000  # 400 KB > 64 KB frame cap
    t = threading.Thread(target=s.sendall, args=(big,))
    t.start()
    srv = server.accept(timeout=5)
    data = bytearray()
    while len(data) < len(big):
        chunk = srv.recv(1 << 16)
        assert chunk
        data += chunk
    t.join()
    assert bytes(data) == big
    client.close()
    server.close()


def _harness(slots=0):
    bls.set_backend("fake_crypto")
    spec = replace(minimal_spec(), altair_fork_epoch=0)
    h = BeaconChainHarness(spec, E, validator_count=16)
    if slots:
        h.extend_chain(slots)
    return h


def test_mux_client_reuses_one_connection():
    a = _harness(slots=8)
    na = NetworkService(a.chain).start()
    try:
        client = RpcClient("127.0.0.1", na.port, mux=True)
        first_conn = None
        for i in range(10):
            status = client.status(na.local_status())
            assert int(status.head_slot) == a.chain.head_state.slot
            assert client.ping(i) >= 1
            if first_conn is None:
                first_conn = client._mux_conn
            assert client._mux_conn is first_conn  # same connection
        blocks = client.blocks_by_range(1, 4, na.decode_block)
        assert blocks
        client.close()
    finally:
        na.stop()


def test_full_stack_muxed_over_noise():
    """Range sync + gossip between two nodes whose RPC substreams ride
    ONE noise-secured muxed connection per peer direction."""
    a = _harness(slots=E.SLOTS_PER_EPOCH)
    b = _harness()
    na = NetworkService(a.chain, transport=NoiseTransport()).start()
    nb = NetworkService(b.chain, transport=NoiseTransport()).start()
    try:
        b.slot_clock.set_slot(a.chain.head_state.slot)
        peer = nb.connect("127.0.0.1", na.port)
        assert peer.client.mux
        nb.sync.sync_with(peer)
        assert b.chain.head_root == a.chain.head_root
        # the whole sync ran over one muxed connection
        assert peer.client._mux_conn is not None and peer.client._mux_conn.alive
        time.sleep(0.2)
        slot = a.chain.head_state.slot + 1
        a.slot_clock.set_slot(slot)
        b.slot_clock.set_slot(slot)
        root, signed = a.add_block_at_slot(slot)
        na.publish_block(signed)
        deadline = time.time() + 10
        while time.time() < deadline and b.chain.head_root != root:
            time.sleep(0.05)
        assert b.chain.head_root == root
    finally:
        na.stop()
        nb.stop()


def test_mux_connection_survives_idle_beyond_dial_timeout():
    """The dial timeout must not linger on the shared connection — an
    idle mux conn stays alive (liveness is per-stream + TCP)."""
    a = _harness(slots=4)
    na = NetworkService(a.chain).start()
    try:
        client = RpcClient("127.0.0.1", na.port, timeout=0.5, mux=True)
        assert client.ping(1) >= 1
        conn = client._mux_conn
        time.sleep(1.2)  # idle for > 2x the dial timeout
        assert conn.alive
        assert client.ping(2) >= 1  # same connection still serves
        assert client._mux_conn is conn
        client.close()
    finally:
        na.stop()


def test_oversized_frame_kills_connection():
    """A wire-claimed length beyond the frame cap must not drive the
    allocation — the connection dies instead."""
    import struct as _struct

    sa, sb = socket.socketpair()
    server = MuxedConnection(sb, initiator=False)
    # handcraft a header claiming a 512 MiB frame
    sa.sendall(_struct.pack(">IBI", 1, 1, 512 << 20))
    deadline = time.time() + 5
    while time.time() < deadline and server.alive:
        time.sleep(0.05)
    assert not server.alive
    sa.close()


def test_unsolicited_syn_on_client_conn_is_reset():
    """An outbound (RPC-client) connection RSTs inbound SYNs instead of
    queueing streams nobody will consume."""
    import struct as _struct

    sa, sb = socket.socketpair()
    client = MuxedConnection(sa, initiator=True)
    # the "server" side speaks raw frames: send SYN for stream 2
    sb.sendall(_struct.pack(">IBI", 2, 1, 0))
    # expect an RST frame for stream 2 back
    hdr = b""
    sb.settimeout(5)
    while len(hdr) < 9:
        hdr += sb.recv(9 - len(hdr))
    sid, flags, length = _struct.unpack(">IBI", hdr)
    assert sid == 2 and flags & 4  # FLAG_RST
    assert not client._streams  # nothing registered
    client.close()
    sb.close()


def test_syn_flood_capped():
    """More concurrent substreams than the cap → RST, not a thread per
    SYN."""
    import struct as _struct
    from lighthouse_tpu.network.mux import MAX_STREAMS_PER_CONN

    sa, sb = socket.socketpair()
    server = MuxedConnection(sb, initiator=False)  # accepts inbound
    for sid in range(1, 2 * MAX_STREAMS_PER_CONN, 2):
        sa.sendall(_struct.pack(">IBI", sid, 1, 0))
    deadline = time.time() + 5
    while time.time() < deadline and len(server._streams) < MAX_STREAMS_PER_CONN:
        time.sleep(0.05)
    time.sleep(0.3)  # let any excess arrive
    assert len(server._streams) <= MAX_STREAMS_PER_CONN
    server.close()
    sa.close()
