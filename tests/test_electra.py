"""Electra: EIP-7251 (maxeb), EIP-7002 (EL withdrawals), EIP-6110
(deposit receipts).

Parity targets: upgrade/electra.rs, beacon_state.rs:2118-2240 churn
helpers, the electra container set, and the electra spec's block/epoch
additions (pending deposit/consolidation queues, compounding-aware
withdrawals)."""

import hashlib
from dataclasses import replace

import pytest

from lighthouse_tpu.crypto import bls
from lighthouse_tpu.state_processing import interop_genesis_state, per_slot_processing
from lighthouse_tpu.state_processing import electra as EL
from lighthouse_tpu.types.chain_spec import FAR_FUTURE_EPOCH, ForkName, minimal_spec
from lighthouse_tpu.types.containers import build_types
from lighthouse_tpu.types.eth_spec import MinimalEthSpec as E

T = build_types(E)


def electra_spec(**kw):
    base = dict(
        altair_fork_epoch=0,
        bellatrix_fork_epoch=0,
        capella_fork_epoch=0,
        deneb_fork_epoch=0,
        electra_fork_epoch=0,
    )
    base.update(kw)
    return replace(minimal_spec(), **base)


def _genesis(spec, n=16):
    bls.set_backend("fake_crypto")
    kps = bls.interop_keypairs(n)
    return interop_genesis_state(kps, 1_600_000_000, b"\x42" * 32, spec, E)


def test_genesis_at_electra_starts_in_electra():
    st = _genesis(electra_spec())
    assert type(st).__name__ == "BeaconStateElectra"
    assert st.deposit_receipts_start_index == 2**64 - 1
    assert st.earliest_exit_epoch >= 1
    assert st.pending_balance_deposits == []
    assert st.fork.current_version == electra_spec().electra_fork_version


def test_upgrade_from_deneb_queues_compounding_excess():
    spec = electra_spec(electra_fork_epoch=1)
    st = _genesis(spec)
    assert type(st).__name__ == "BeaconStateDeneb"
    # make validator 0 a compounding early adopter with excess balance
    st.validators[0].withdrawal_credentials = b"\x02" + b"\x00" * 11 + b"\xaa" * 20
    st.balances[0] = 40_000_000_000
    while st.slot < E.SLOTS_PER_EPOCH:
        per_slot_processing(st, spec, E)
    assert type(st).__name__ == "BeaconStateElectra"
    # excess over MIN_ACTIVATION_BALANCE queued, balance clamped
    assert st.balances[0] == spec.min_activation_balance
    assert any(
        d.index == 0 and d.amount == 8_000_000_000
        for d in st.pending_balance_deposits
    )


def test_electra_state_ssz_roundtrip():
    st = _genesis(electra_spec())
    st.pending_balance_deposits.append(T.PendingBalanceDeposit(index=1, amount=5))
    st.pending_partial_withdrawals.append(
        T.PendingPartialWithdrawal(index=2, amount=7, withdrawable_epoch=9)
    )
    st.pending_consolidations.append(
        T.PendingConsolidation(source_index=1, target_index=2)
    )
    data = st.serialize()
    back = type(st).deserialize(data)
    assert back.hash_tree_root() == st.hash_tree_root()
    assert back.pending_partial_withdrawals[0].withdrawable_epoch == 9


def test_deposit_receipt_flows_through_pending_queue():
    spec = electra_spec()
    st = _genesis(spec)
    n0 = len(st.validators)
    kp = bls.interop_keypairs(n0 + 1)[-1]
    from lighthouse_tpu.state_processing.genesis import build_deposit_data

    data = build_deposit_data(kp, 32_000_000_000, spec, E)
    receipt = T.DepositReceipt(
        pubkey=data.pubkey,
        withdrawal_credentials=data.withdrawal_credentials,
        amount=data.amount,
        signature=data.signature,
        index=77,
    )
    EL.process_deposit_receipt(st, receipt, spec, E)
    assert st.deposit_receipts_start_index == 77
    assert len(st.validators) == n0 + 1
    v = st.validators[-1]
    assert v.effective_balance == 0 and st.balances[-1] == 0
    assert st.pending_balance_deposits[-1].amount == 32_000_000_000

    # epoch processing applies the pending deposit (churn permitting)
    EL.process_pending_balance_deposits(st, spec, E)
    assert st.balances[-1] == 32_000_000_000
    assert st.pending_balance_deposits == []


def test_el_withdrawal_request_full_exit():
    spec = electra_spec()
    st = _genesis(spec)
    addr = b"\xaa" * 20
    v = st.validators[3]
    v.withdrawal_credentials = b"\x01" + b"\x00" * 11 + addr
    # age the validator past shard_committee_period
    st.slot = (spec.shard_committee_period + 2) * E.SLOTS_PER_EPOCH
    req = T.ExecutionLayerWithdrawalRequest(
        source_address=addr,
        validator_pubkey=v.pubkey,
        amount=spec.full_exit_request_amount,
    )
    EL.process_execution_layer_withdrawal_request(st, req, spec, E)
    assert st.validators[3].exit_epoch != FAR_FUTURE_EPOCH

    # wrong source address is ignored
    v5 = st.validators[5]
    v5.withdrawal_credentials = b"\x01" + b"\x00" * 11 + addr
    bad = T.ExecutionLayerWithdrawalRequest(
        source_address=b"\xbb" * 20,
        validator_pubkey=v5.pubkey,
        amount=spec.full_exit_request_amount,
    )
    EL.process_execution_layer_withdrawal_request(st, bad, spec, E)
    assert st.validators[5].exit_epoch == FAR_FUTURE_EPOCH


def test_el_withdrawal_request_partial_compounding():
    spec = electra_spec()
    st = _genesis(spec)
    addr = b"\xcc" * 20
    v = st.validators[2]
    v.withdrawal_credentials = b"\x02" + b"\x00" * 11 + addr
    v.effective_balance = spec.min_activation_balance
    st.balances[2] = spec.min_activation_balance + 3_000_000_000
    st.slot = (spec.shard_committee_period + 2) * E.SLOTS_PER_EPOCH
    req = T.ExecutionLayerWithdrawalRequest(
        source_address=addr, validator_pubkey=v.pubkey, amount=2_000_000_000
    )
    EL.process_execution_layer_withdrawal_request(st, req, spec, E)
    assert len(st.pending_partial_withdrawals) == 1
    w = st.pending_partial_withdrawals[0]
    assert w.index == 2 and w.amount == 2_000_000_000
    assert st.validators[2].exit_epoch == FAR_FUTURE_EPOCH  # not an exit

    # matured partials lead get_expected_withdrawals
    st_m = st.copy()
    st_m.slot = (w.withdrawable_epoch + 1) * E.SLOTS_PER_EPOCH
    withdrawals, partials = EL.get_expected_withdrawals_electra(st_m, spec, E)
    assert partials == 1
    assert withdrawals[0].validator_index == 2
    assert withdrawals[0].amount == 2_000_000_000


def test_multiple_pending_partials_cap_against_remaining_excess():
    """Several matured queue entries for ONE validator: each must be
    capped against the balance REMAINING after the withdrawals already
    produced this sweep (spec total_withdrawn deduction) — a per-entry
    cap against the undecremented balance would overdraw the validator
    and blow the stage-2 sweep's balance arithmetic."""
    spec = electra_spec()
    st = _genesis(spec)
    addr = b"\xdd" * 20
    v = st.validators[4]
    v.withdrawal_credentials = b"\x02" + b"\x00" * 11 + addr
    v.effective_balance = spec.min_activation_balance
    excess = 5_000_000_000
    st.balances[4] = spec.min_activation_balance + excess
    # three matured 3 ETH requests against 5 ETH of excess
    for _ in range(3):
        st.pending_partial_withdrawals.append(
            T.PendingPartialWithdrawal(
                index=4, amount=3_000_000_000, withdrawable_epoch=0
            )
        )
    st.slot = 2 * E.SLOTS_PER_EPOCH
    withdrawals, partials = EL.get_expected_withdrawals_electra(st, spec, E)
    assert partials == 3
    mine = [w for w in withdrawals if w.validator_index == 4]
    # 3 + 2 + 0: the third entry sees no remaining excess
    assert [w.amount for w in mine] == [3_000_000_000, 2_000_000_000]
    assert sum(w.amount for w in mine) == excess
    # the same call runs the stage-2 sweep over the decremented
    # balances — reaching here proves its safe_sub stayed in range


def test_pending_consolidations_transfer_balance():
    spec = electra_spec()
    st = _genesis(spec)
    st.pending_consolidations.append(
        T.PendingConsolidation(source_index=1, target_index=2)
    )
    # source must be withdrawable for the transfer to fire
    st.validators[1].withdrawable_epoch = 0
    b1, b2 = st.balances[1], st.balances[2]
    EL.process_pending_consolidations(st, spec, E)
    moved = min(b1, spec.min_activation_balance)
    assert st.balances[1] == b1 - moved
    assert st.balances[2] == b2 + moved
    assert st.pending_consolidations == []


def test_effective_balance_updates_compounding_cap():
    spec = electra_spec()
    st = _genesis(spec)
    st.validators[0].withdrawal_credentials = b"\x02" + b"\x00" * 31
    st.balances[0] = 100_000_000_000  # 100 ETH
    EL.process_effective_balance_updates_electra(st, spec, E)
    assert st.validators[0].effective_balance == 100_000_000_000  # no 32 cap
    # non-compounding stays capped at MIN_ACTIVATION_BALANCE
    st.balances[1] = 100_000_000_000
    EL.process_effective_balance_updates_electra(st, spec, E)
    assert st.validators[1].effective_balance == spec.min_activation_balance


def test_chain_crosses_into_electra_and_finalizes():
    """Cross-fork e2e with a real (mock) execution layer: the chain ends in
    BeaconStateElectra with hash-linked electra payloads and finality
    advancing (the VERDICT 'done' criterion for this component)."""
    from lighthouse_tpu.beacon_chain.harness import BeaconChainHarness

    spec = replace(
        minimal_spec(),
        altair_fork_epoch=0,
        bellatrix_fork_epoch=0,
        capella_fork_epoch=1,
        deneb_fork_epoch=2,
        electra_fork_epoch=3,
    )
    h = BeaconChainHarness(
        spec, E, validator_count=16, mock_execution_layer=True
    )
    h.extend_chain(6 * E.SLOTS_PER_EPOCH)
    st = h.chain.head_state
    assert type(st).__name__ == "BeaconStateElectra"
    assert h.finalized_epoch >= 4
    header = st.latest_execution_payload_header
    assert header.block_hash != b"\x00" * 32
    assert hasattr(header, "deposit_receipts_root")
