"""Altair→Deneb state transition tests: fork upgrades, participation-flag
epoch processing (vectorized vs scalar-spec parity), sync committees,
withdrawals.

The parity tests re-implement the spec formulas index-by-index in plain
Python and require the vectorized numpy sweep to match exactly — the same
oracle discipline the device kernels use against host implementations.
"""

import random
from dataclasses import replace

import pytest

from lighthouse_tpu.crypto import bls
from lighthouse_tpu.state_processing import per_slot_processing
from lighthouse_tpu.state_processing.altair import (
    PARTICIPATION_FLAG_WEIGHTS,
    TIMELY_HEAD_FLAG_INDEX,
    TIMELY_TARGET_FLAG_INDEX,
    WEIGHT_DENOMINATOR,
    get_base_reward_per_increment,
    process_inactivity_updates,
    process_rewards_and_penalties_altair,
)
from lighthouse_tpu.state_processing.capella import (
    get_expected_withdrawals,
)
from lighthouse_tpu.state_processing.genesis import interop_genesis_state
from lighthouse_tpu.state_processing.per_epoch import get_finality_delay
from lighthouse_tpu.types.chain_spec import ForkName, minimal_spec
from lighthouse_tpu.types.containers import build_types
from lighthouse_tpu.types.eth_spec import MinimalEthSpec

E = MinimalEthSpec
T = build_types(E)


@pytest.fixture(autouse=True)
def fake_crypto():
    old = bls.backend_name()
    bls.set_backend("fake_crypto")
    yield
    bls.set_backend(old)


def altair_spec(**forks):
    base = dict(altair_fork_epoch=0)
    base.update(forks)
    return replace(minimal_spec(), **base)


def make_altair_state(n=16, spec=None):
    spec = spec or altair_spec()
    kps = bls.interop_keypairs(n)
    return interop_genesis_state(kps, 1_600_000_000, b"\x42" * 32, spec, E), spec


def randomize_participation(state, rng):
    n = len(state.validators)
    state.previous_epoch_participation = bytearray(
        rng.randrange(8) for _ in range(n)
    )
    state.current_epoch_participation = bytearray(
        rng.randrange(8) for _ in range(n)
    )
    state.inactivity_scores = [rng.randrange(100) for _ in range(n)]
    for i in range(n):
        state.balances[i] = 31_000_000_000 + rng.randrange(2_000_000_000)
    # a couple of slashed validators
    state.validators[1].slashed = True
    state.validators[1].withdrawable_epoch = 9999


# --- upgrades ---------------------------------------------------------------


def test_genesis_at_fork_starts_in_that_fork():
    for fork, cls_name in [
        (dict(altair_fork_epoch=0), "BeaconStateAltair"),
        (
            dict(altair_fork_epoch=0, bellatrix_fork_epoch=0),
            "BeaconStateBellatrix",
        ),
        (
            dict(
                altair_fork_epoch=0,
                bellatrix_fork_epoch=0,
                capella_fork_epoch=0,
                deneb_fork_epoch=0,
            ),
            "BeaconStateDeneb",
        ),
    ]:
        state, _ = make_altair_state(8, altair_spec(**fork))
        assert type(state).__name__ == cls_name


def test_upgrade_preserves_registry_and_sets_new_fields():
    spec = replace(minimal_spec(), altair_fork_epoch=1)
    kps = bls.interop_keypairs(8)
    state = interop_genesis_state(kps, 1_600_000_000, b"\x42" * 32, spec, E)
    assert type(state).__name__ == "BeaconState"
    pre_validators = [v.pubkey for v in state.validators]
    pre_balances = list(state.balances)
    while state.slot < E.SLOTS_PER_EPOCH:
        per_slot_processing(state, spec, E)
    assert type(state).__name__ == "BeaconStateAltair"
    assert [v.pubkey for v in state.validators] == pre_validators
    assert len(state.inactivity_scores) == 8
    assert len(state.previous_epoch_participation) == 8
    assert state.fork.current_version == spec.altair_fork_version
    assert state.fork.previous_version == spec.genesis_fork_version
    assert len(state.current_sync_committee.pubkeys) == E.SYNC_COMMITTEE_SIZE
    # registry preserved up to rewards/penalties applied at the boundary
    assert len(state.balances) == len(pre_balances)
    # state still hashes and round-trips
    root = state.hash_tree_root()
    data = type(state).serialize_value(state)
    back = type(state).deserialize(data)
    assert type(state).hash_tree_root_of(back) == root


def test_upgrade_chain_through_deneb():
    spec = replace(
        minimal_spec(),
        altair_fork_epoch=1,
        bellatrix_fork_epoch=2,
        capella_fork_epoch=2,
        deneb_fork_epoch=3,
    )
    kps = bls.interop_keypairs(8)
    state = interop_genesis_state(kps, 1_600_000_000, b"\x42" * 32, spec, E)
    while state.slot < 3 * E.SLOTS_PER_EPOCH:
        per_slot_processing(state, spec, E)
    assert type(state).__name__ == "BeaconStateDeneb"
    hdr = state.latest_execution_payload_header
    assert hdr.blob_gas_used == 0
    assert state.next_withdrawal_index == 0
    state.hash_tree_root()


# --- vectorized epoch processing parity ------------------------------------


def _scalar_flag_deltas(state, spec, E, fork):
    """Straight-from-spec per-index implementation (altair/beacon-chain.md
    get_flag_index_deltas + get_inactivity_penalty_deltas)."""
    from lighthouse_tpu.state_processing.accessors import (
        get_current_epoch,
        get_previous_epoch,
        is_active_validator,
    )

    n = len(state.validators)
    current = get_current_epoch(state, E)
    previous = get_previous_epoch(state, E)
    in_leak = get_finality_delay(state, E) > E.MIN_EPOCHS_TO_INACTIVITY_PENALTY
    rewards = [0] * n
    penalties = [0] * n

    def active_prev(v):
        return is_active_validator(v, previous)

    def eligible(i):
        v = state.validators[i]
        return active_prev(v) or (
            v.slashed and previous + 1 < v.withdrawable_epoch
        )

    total_active = max(
        sum(
            v.effective_balance
            for v in state.validators
            if is_active_validator(v, current)
        ),
        E.EFFECTIVE_BALANCE_INCREMENT,
    )
    from lighthouse_tpu.state_processing.accessors import int_sqrt

    brpi = E.EFFECTIVE_BALANCE_INCREMENT * E.BASE_REWARD_FACTOR // int_sqrt(
        total_active
    )
    tai = total_active // E.EFFECTIVE_BALANCE_INCREMENT

    for flag_index, weight in enumerate(PARTICIPATION_FLAG_WEIGHTS):
        unslashed = [
            i
            for i in range(n)
            if active_prev(state.validators[i])
            and not state.validators[i].slashed
            and state.previous_epoch_participation[i] & (1 << flag_index)
        ]
        upb = max(
            sum(state.validators[i].effective_balance for i in unslashed),
            E.EFFECTIVE_BALANCE_INCREMENT,
        )
        upi = upb // E.EFFECTIVE_BALANCE_INCREMENT
        uset = set(unslashed)
        for i in range(n):
            if not eligible(i):
                continue
            base_reward = (
                state.validators[i].effective_balance
                // E.EFFECTIVE_BALANCE_INCREMENT
                * brpi
            )
            if i in uset:
                if not in_leak:
                    rewards[i] += (
                        base_reward * weight * upi // (tai * WEIGHT_DENOMINATOR)
                    )
            elif flag_index != TIMELY_HEAD_FLAG_INDEX:
                penalties[i] += base_reward * weight // WEIGHT_DENOMINATOR

    quotient = (
        E.INACTIVITY_PENALTY_QUOTIENT_BELLATRIX
        if fork >= ForkName.BELLATRIX
        else E.INACTIVITY_PENALTY_QUOTIENT_ALTAIR
    )
    for i in range(n):
        if not eligible(i):
            continue
        v = state.validators[i]
        participated = (
            active_prev(v)
            and not v.slashed
            and state.previous_epoch_participation[i]
            & (1 << TIMELY_TARGET_FLAG_INDEX)
        )
        if not participated:
            penalty_numerator = (
                v.effective_balance * state.inactivity_scores[i]
            )
            penalties[i] += penalty_numerator // (
                spec.inactivity_score_bias * quotient
            )
    return rewards, penalties


@pytest.mark.parametrize("fork", [ForkName.ALTAIR, ForkName.BELLATRIX])
@pytest.mark.parametrize("seed", [1, 2, 3])
def test_rewards_and_penalties_vectorized_matches_scalar(fork, seed):
    rng = random.Random(seed)
    state, spec = make_altair_state(24)
    # advance past epoch 1 so previous-epoch logic is live
    while state.slot < 2 * E.SLOTS_PER_EPOCH + 3:
        per_slot_processing(state, spec, E)
    randomize_participation(state, rng)

    expected = list(state.balances)
    rewards, penalties = _scalar_flag_deltas(state, spec, E, fork)
    for i in range(len(expected)):
        expected[i] = max(expected[i] + rewards[i] - penalties[i], 0)

    process_rewards_and_penalties_altair(state, spec, E, fork)
    assert list(state.balances) == expected


@pytest.mark.parametrize("seed", [5, 6])
def test_inactivity_updates_vectorized_matches_scalar(seed):
    from lighthouse_tpu.state_processing.accessors import (
        get_previous_epoch,
        is_active_validator,
    )

    rng = random.Random(seed)
    state, spec = make_altair_state(24)
    while state.slot < 2 * E.SLOTS_PER_EPOCH + 3:
        per_slot_processing(state, spec, E)
    randomize_participation(state, rng)

    previous = get_previous_epoch(state, E)
    in_leak = get_finality_delay(state, E) > E.MIN_EPOCHS_TO_INACTIVITY_PENALTY
    expected = list(state.inactivity_scores)
    for i, v in enumerate(state.validators):
        eligible = is_active_validator(v, previous) or (
            v.slashed and previous + 1 < v.withdrawable_epoch
        )
        if not eligible:
            continue
        participated = (
            is_active_validator(v, previous)
            and not v.slashed
            and state.previous_epoch_participation[i]
            & (1 << TIMELY_TARGET_FLAG_INDEX)
        )
        if participated:
            expected[i] -= min(1, expected[i])
        else:
            expected[i] += spec.inactivity_score_bias
        if not in_leak:
            expected[i] -= min(
                spec.inactivity_score_recovery_rate, expected[i]
            )

    process_inactivity_updates(state, spec, E)
    assert list(state.inactivity_scores) == expected


# --- sync committee ---------------------------------------------------------


def test_sync_committee_membership_is_registry_subset():
    state, _ = make_altair_state(16)
    registry = {bytes(v.pubkey) for v in state.validators}
    for pk in state.current_sync_committee.pubkeys:
        assert bytes(pk) in registry


def test_sync_aggregate_rewards_flow():
    from lighthouse_tpu.state_processing.altair import process_sync_aggregate
    from lighthouse_tpu.state_processing.per_block import ConsensusContext

    state, spec = make_altair_state(16)
    per_slot_processing(state, spec, E)
    ctxt = ConsensusContext(state.slot)
    pre_balances = list(state.balances)
    bits = [True] * E.SYNC_COMMITTEE_SIZE
    aggregate = T.SyncAggregate(
        sync_committee_bits=bits,
        sync_committee_signature=bls.INFINITY_SIGNATURE,
    )
    process_sync_aggregate(state, aggregate, spec, E, False, ctxt)
    brpi = get_base_reward_per_increment(state, E)
    assert brpi > 0
    assert sum(state.balances) > sum(pre_balances)  # full participation pays

    # all-empty: everyone in the committee is penalized
    state2, _ = make_altair_state(16)
    per_slot_processing(state2, spec, E)
    pre2 = sum(state2.balances)
    empty = T.SyncAggregate(
        sync_committee_bits=[False] * E.SYNC_COMMITTEE_SIZE,
        sync_committee_signature=bls.INFINITY_SIGNATURE,
    )
    process_sync_aggregate(state2, empty, spec, E, False, ctxt)
    assert sum(state2.balances) < pre2


# --- capella withdrawals ----------------------------------------------------


def test_expected_withdrawals_sweep():
    spec = altair_spec(
        bellatrix_fork_epoch=0, capella_fork_epoch=0
    )
    state, _ = make_altair_state(8, spec)
    assert type(state).__name__ == "BeaconStateCapella"
    # give validator 2 an eth1 credential + excess balance (partial)
    v = state.validators[2]
    v.withdrawal_credentials = b"\x01" + b"\x00" * 11 + b"\xaa" * 20
    state.balances[2] = E.MAX_EFFECTIVE_BALANCE + 7
    # validator 3: fully withdrawable
    v3 = state.validators[3]
    v3.withdrawal_credentials = b"\x01" + b"\x00" * 11 + b"\xbb" * 20
    v3.withdrawable_epoch = 0
    ws = get_expected_withdrawals(state, E)
    assert [w.validator_index for w in ws] == [2, 3]
    assert ws[0].amount == 7
    assert ws[1].amount == state.balances[3]
    assert bytes(ws[1].address) == b"\xbb" * 20


def test_withdrawals_applied_in_block_flow():
    from lighthouse_tpu.state_processing.capella import process_withdrawals

    spec = altair_spec(bellatrix_fork_epoch=0, capella_fork_epoch=0)
    state, _ = make_altair_state(8, spec)
    v = state.validators[4]
    v.withdrawal_credentials = b"\x01" + b"\x00" * 11 + b"\xcc" * 20
    state.balances[4] = E.MAX_EFFECTIVE_BALANCE + 123
    expected = get_expected_withdrawals(state, E)
    payload = T.ExecutionPayloadCapella(withdrawals=expected)
    process_withdrawals(state, payload, E)
    assert state.balances[4] == E.MAX_EFFECTIVE_BALANCE
    assert state.next_withdrawal_index == 1

    # wrong withdrawals must be rejected
    from lighthouse_tpu.state_processing.per_block import BlockProcessingError

    state2, _ = make_altair_state(8, spec)
    state2.validators[4].withdrawal_credentials = (
        b"\x01" + b"\x00" * 11 + b"\xcc" * 20
    )
    state2.balances[4] = E.MAX_EFFECTIVE_BALANCE + 123
    bad = T.ExecutionPayloadCapella(withdrawals=[])
    with pytest.raises(BlockProcessingError):
        process_withdrawals(state2, bad, E)


# --- full-chain cross-fork runs --------------------------------------------


def test_chain_crosses_all_forks_and_finalizes():
    """Harness drives one block per slot through phase0→altair→bellatrix→
    capella→deneb and finality keeps advancing (the reference's
    fork-transition beacon-chain tests)."""
    from lighthouse_tpu.beacon_chain.harness import BeaconChainHarness

    spec = replace(
        minimal_spec(),
        altair_fork_epoch=1,
        bellatrix_fork_epoch=2,
        capella_fork_epoch=3,
        deneb_fork_epoch=4,
    )
    h = BeaconChainHarness(spec, E, validator_count=16)
    h.extend_chain(6 * E.SLOTS_PER_EPOCH)
    st = h.chain.head_state
    assert type(st).__name__ == "BeaconStateDeneb"
    assert h.finalized_epoch >= 4
    # participation-flag bookkeeping stayed registry-shaped
    assert len(st.previous_epoch_participation) == len(st.validators)
    assert len(st.inactivity_scores) == len(st.validators)


@pytest.mark.slow
def test_chain_altair_real_crypto():
    """Sync-aggregate + attestation signatures verify under the real BLS
    backend across the altair boundary."""
    from lighthouse_tpu.beacon_chain.harness import BeaconChainHarness

    bls.set_backend("host")
    try:
        spec = replace(minimal_spec(), altair_fork_epoch=1)
        h = BeaconChainHarness(spec, E, validator_count=8)
        h.extend_chain(3 * E.SLOTS_PER_EPOCH + 2)
        assert type(h.chain.head_state).__name__ == "BeaconStateAltair"
        assert h.chain.justified_checkpoint.epoch >= 2
    finally:
        bls.set_backend("fake_crypto")
