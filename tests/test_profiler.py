"""Continuous profiling: span-attributed stack sampler + endpoints.

PR 10's acceptance suite: the sampler is off by default and leaks zero
threads, samples attribute to the innermost active span (including
across the beacon_processor `copy_context` worker hop), the collapsed /
speedscope exports hold their golden shapes, `/lighthouse/profile` and
`/lighthouse/health` serve from BOTH the MetricsServer and the Beacon
API, bench --compare flags regressions, and a perf_smoke bound keeps
sampled block-import wall time within 1.10× of unsampled."""

import json
import threading
import time
import urllib.error
import urllib.request
from dataclasses import replace

import pytest

from lighthouse_tpu.beacon_processor import BeaconProcessor, WorkType
from lighthouse_tpu.crypto import bls
from lighthouse_tpu.metrics import REGISTRY
from lighthouse_tpu.metrics.profiler import (
    PROFILER,
    StackProfiler,
    maybe_start_profiler,
)
from lighthouse_tpu.types.chain_spec import minimal_spec
from lighthouse_tpu.types.eth_spec import MinimalEthSpec as E
from lighthouse_tpu.utils import tracing
from lighthouse_tpu.utils.tracing import adopt_thread_span, span


def _harness(slots=0, validator_count=16):
    from lighthouse_tpu.beacon_chain.harness import BeaconChainHarness

    bls.set_backend("fake_crypto")
    spec = replace(minimal_spec(), altair_fork_epoch=0)
    h = BeaconChainHarness(spec, E, validator_count=validator_count)
    if slots:
        h.extend_chain(slots, attest=False)
    return h


# -- off by default / zero thread leak ---------------------------------------


def test_profiler_off_by_default_no_threads(monkeypatch):
    monkeypatch.delenv("LIGHTHOUSE_TPU_PROFILE", raising=False)
    before = threading.active_count()
    assert maybe_start_profiler() is None
    assert not PROFILER.running
    # server starts must not arm it either
    from lighthouse_tpu.metrics.server import MetricsServer

    srv = MetricsServer().start()
    try:
        assert not PROFILER.running
        assert not any(
            t.name == "stack-profiler" for t in threading.enumerate()
        )
    finally:
        srv.stop()
        srv._thread.join(timeout=2.0)
    # the only threads that came and went were the server's own
    assert threading.active_count() <= before + 1


def test_profiler_start_stop_no_thread_leak():
    p = StackProfiler(hz=200)
    before = threading.active_count()
    p.start()
    assert p.running
    assert any(t.name == "stack-profiler" for t in threading.enumerate())
    p.stop()
    assert not p.running
    assert threading.active_count() == before
    # idempotent stop, restartable
    p.stop()
    p.start()
    p.stop()
    assert threading.active_count() == before


# -- span attribution --------------------------------------------------------


def test_sample_attributes_to_innermost_span_root():
    p = StackProfiler(hz=100)
    with span("obs_prof_root"):
        with span("inner_stage"):
            assert p.sample_once() >= 1
    snap = p.snapshot()
    # attribution is by the TRACE ROOT name, not the innermost span name
    assert "obs_prof_root" in snap
    (stack, count), *_ = sorted(
        snap["obs_prof_root"].items(), key=lambda kv: -kv[1]
    )
    assert count >= 1
    assert stack.startswith("thread:")
    assert "sample_once" in stack  # the sampled frame chain reached here


def test_sample_without_span_is_unattributed():
    p = StackProfiler(hz=100)
    assert tracing.thread_spans().get(threading.get_ident()) is None
    p.sample_once()
    assert "unattributed" in p.snapshot()
    assert REGISTRY.counter("profiler_samples_total").value(
        root="unattributed"
    ) > 0


def test_thread_registry_restores_on_exit():
    ident = threading.get_ident()
    with span("outer_reg") as outer:
        assert tracing.thread_spans()[ident] is outer
        with span("inner_reg") as inner:
            assert tracing.thread_spans()[ident] is inner
        assert tracing.thread_spans()[ident] is outer
    assert ident not in tracing.thread_spans()


def test_adopt_thread_span_attribution():
    """The worker-hop primitive in isolation: a foreign span adopted for
    a block attributes this thread's samples to its root."""
    p = StackProfiler(hz=100)
    foreign = span("obs_adopt_root")
    with foreign:
        pass  # closed; adoption only reads root_name
    ident = threading.get_ident()
    with adopt_thread_span(foreign):
        assert tracing.thread_spans()[ident] is foreign
        p.sample_once()
    assert ident not in tracing.thread_spans()
    assert "obs_adopt_root" in p.snapshot()


def test_worker_hop_samples_attribute_to_submitting_root():
    """The beacon_processor contract: a handler running on a worker
    thread (inside the submitter's copied context) is sampled under the
    SUBMITTING span's root even while outside any span of its own."""
    p = StackProfiler(hz=100)
    bp = BeaconProcessor(num_workers=1, name="prof-test")
    sampled = threading.Event()

    def handler(item):
        # no span opened here: attribution must come from adoption
        p.sample_once()
        sampled.set()

    try:
        with span("obs_prof_submit_root"):
            assert bp.submit(WorkType.API_REQUEST, "x", handler)
            assert bp.drain(timeout=5.0)
        assert sampled.wait(timeout=1.0)
    finally:
        bp.shutdown()
    snap = p.snapshot()
    assert "obs_prof_submit_root" in snap
    stacks = "\n".join(snap["obs_prof_submit_root"])
    # the worker thread's kind rides the folded stack
    assert "thread:prof-test-w" in stacks


# -- export golden shapes ----------------------------------------------------


def _populated_profiler():
    p = StackProfiler(hz=100)
    with span("obs_prof_shape"):
        for _ in range(3):
            p.sample_once()
    p.sample_once()  # one unattributed sweep
    return p


def test_collapsed_golden_shape():
    p = _populated_profiler()
    text = p.collapsed()
    assert text.endswith("\n")
    for line in text.strip().splitlines():
        stack, _, count = line.rpartition(" ")
        assert int(count) >= 1
        parts = stack.split(";")
        # root;thread:<kind>;frames...
        assert len(parts) >= 3
        assert parts[1].startswith("thread:")
    roots = {line.split(";", 1)[0] for line in text.strip().splitlines()}
    assert {"obs_prof_shape", "unattributed"} <= roots
    # root filter narrows to one root
    only = p.collapsed("obs_prof_shape")
    assert all(
        line.startswith("obs_prof_shape;")
        for line in only.strip().splitlines()
    )


def test_speedscope_golden_shape():
    p = _populated_profiler()
    doc = p.speedscope()
    assert set(doc) == {"$schema", "shared", "profiles", "name", "exporter"}
    assert doc["$schema"] == "https://www.speedscope.app/file-format-schema.json"
    assert set(doc["shared"]) == {"frames"}
    names = [prof["name"] for prof in doc["profiles"]]
    assert "obs_prof_shape" in names and "unattributed" in names
    nframes = len(doc["shared"]["frames"])
    for prof in doc["profiles"]:
        assert set(prof) == {
            "type", "name", "unit", "startValue", "endValue", "samples",
            "weights",
        }
        assert prof["type"] == "sampled" and prof["unit"] == "none"
        assert len(prof["samples"]) == len(prof["weights"])
        assert prof["endValue"] == float(sum(prof["weights"]))
        for s in prof["samples"]:
            assert all(0 <= i < nframes for i in s)
    json.dumps(doc)  # JSON-serializable as-is


def test_root_other_query_covers_non_taxonomy_roots():
    """profiler_samples_total folds non-taxonomy roots into its `other`
    label; a `root=other` query must return those same roots' stacks so
    the metric's aggregate and the endpoint agree."""
    p = StackProfiler(hz=100)
    with span("obs_nontaxonomy_root"):
        p.sample_once()
    snap = p.snapshot("other")
    assert "obs_nontaxonomy_root" in snap
    # taxonomy roots and the unattributed bucket are NOT in `other`
    with span("block_import"):
        p.sample_once()
    p.sample_once()  # unattributed sweep
    snap = p.snapshot("other")
    assert "block_import" not in snap and "unattributed" not in snap


def test_top_stacks_and_decay_bounds():
    p = StackProfiler(hz=100, max_stacks_per_root=4)
    with p._lock:
        p._stacks["obs_decay_root"] = {f"thread:t;f{i}": float(i + 1)
                                       for i in range(40)}
        p._samples_since_decay = 10 ** 9
        p._decay_locked()
    per = p.snapshot()["obs_decay_root"]
    assert len(per) <= 4  # pruned back to the per-root bound
    assert max(per.values()) == 20  # counts halved
    top = p.top_stacks(n=2)["obs_decay_root"]
    assert [e["samples"] for e in top] == sorted(
        (e["samples"] for e in top), reverse=True
    )
    assert set(top[0]) == {"stack", "samples"}


# -- endpoints on both servers -----------------------------------------------


def test_profile_endpoint_disabled_returns_503(monkeypatch):
    from lighthouse_tpu.metrics import profiler as profiler_mod
    from lighthouse_tpu.metrics.server import MetricsServer

    monkeypatch.delenv("LIGHTHOUSE_TPU_PROFILE", raising=False)
    monkeypatch.setattr(profiler_mod, "PROFILER", StackProfiler())
    srv = MetricsServer().start()
    try:
        with pytest.raises(urllib.error.HTTPError) as exc_info:
            urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/lighthouse/profile"
            )
        assert exc_info.value.code == 503
    finally:
        srv.stop()


def test_health_endpoint_on_both_servers():
    from lighthouse_tpu.http_api import HttpApiServer
    from lighthouse_tpu.metrics.server import MetricsServer

    h = _harness()
    msrv = MetricsServer().start()
    asrv = HttpApiServer(h.chain).start()
    api_traces_before = REGISTRY.counter("trace_collector_traces_total").value(
        root="api_request"
    )
    try:
        for port in (msrv.port, asrv.port):
            doc = json.load(
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/lighthouse/health"
                )
            )["data"]
            assert doc["uptime_seconds"] > 0
            assert doc["rss_bytes"] > 0
            assert doc["peak_rss_bytes"] >= doc["rss_bytes"] > 0
            assert doc["threads"] >= 2
            assert len(doc["gc"]["counts"]) == 3
            assert len(doc["gc"]["collections"]) == 3
            assert 0.0 <= doc["worker_busy_ratio"] <= 1.0
            assert "sync_state" in doc and "trace_ring_size" in doc
            assert set(doc["profiler"]) == {"running", "samples"}
            assert "total_memory_bytes" in doc["system"]
    finally:
        msrv.stop()
        asrv.stop()
    # observability reads never mint api_request traces
    assert (
        REGISTRY.counter("trace_collector_traces_total").value(
            root="api_request"
        )
        == api_traces_before
    )


# -- THE acceptance sim ------------------------------------------------------


def test_gossip_driven_import_profiles_to_block_import(monkeypatch):
    """Acceptance: with LIGHTHOUSE_TPU_PROFILE=1 a gossip-driven block
    import sim yields ≥1 block_import-attributed stack retrievable as
    collapsed text AND speedscope JSON from both servers, and worker-hop
    (chain-segment) samples attribute to sync_range_batch rather than
    the unattributed bucket."""
    from lighthouse_tpu.http_api import HttpApiServer
    from lighthouse_tpu.metrics import profiler as profiler_mod
    from lighthouse_tpu.metrics.server import MetricsServer
    from lighthouse_tpu.network import NetworkService

    monkeypatch.setenv("LIGHTHOUSE_TPU_PROFILE", "1")
    # dense sampling so single-digit-ms minimal-preset imports land
    monkeypatch.setenv("LIGHTHOUSE_TPU_PROFILE_HZ", "750")
    a = _harness(slots=E.SLOTS_PER_EPOCH)
    b = _harness()
    msrv = MetricsServer().start()  # arms the sampler from the env
    asrv = HttpApiServer(b.chain).start()
    prof = profiler_mod.PROFILER
    assert prof.running
    na = NetworkService(a.chain, heartbeat_interval=None).start()
    nb = NetworkService(b.chain, heartbeat_interval=None).start()
    try:
        # range-sync catch-up: imports ride the beacon_processor
        # CHAIN_SEGMENT lane — the copy_context worker hop under test
        b.slot_clock.set_slot(a.chain.head_state.slot)
        peer = nb.connect("127.0.0.1", na.port)
        assert nb.sync.sync_with(peer) == E.SLOTS_PER_EPOCH
        time.sleep(0.2)  # let A's inbound-peer registration settle

        # alternate the two import paths until both show up in the
        # profile: gossip-published blocks (block_import ROOT spans on
        # B's gossip handler thread) and quiet extensions pulled through
        # range sync (CHAIN_SEGMENT worker lane → sync_range_batch)
        def worker_attributed(snap):
            return any(
                "thread:network_beacon_processor-w" in s
                for s in snap.get("sync_range_batch", ())
            )

        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            snap = prof.snapshot()
            if "block_import" in snap and worker_attributed(snap):
                break
            # one gossip-driven import
            slot = a.chain.head_state.slot + 1
            a.slot_clock.set_slot(slot)
            b.slot_clock.set_slot(slot)
            root, signed = a.add_block_at_slot(slot)
            na.publish_block(signed)
            arrival = time.monotonic() + 5.0
            while time.monotonic() < arrival and b.chain.head_root != root:
                time.sleep(0.02)
            assert b.chain.head_root == root
            # a quiet 4-slot extension, range-synced through the workers
            for _ in range(4):
                slot = a.chain.head_state.slot + 1
                a.slot_clock.set_slot(slot)
                a.add_block_at_slot(slot)
            b.slot_clock.set_slot(a.chain.head_state.slot)
            nb.sync.sync_with(peer)
        snap = prof.snapshot()
        assert "block_import" in snap, f"roots sampled: {sorted(snap)}"
        # worker-hop attribution: chain-segment samples landed under the
        # sync_range_batch root, NOT in the unattributed bucket
        assert worker_attributed(snap), (
            f"roots sampled: {sorted(snap)}; sync stacks: "
            f"{sorted(snap.get('sync_range_batch', ()))[:4]}"
        )

        # retrievable from BOTH servers, both formats
        for port in (msrv.port, asrv.port):
            text = (
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}"
                    "/lighthouse/profile?root=block_import&format=collapsed"
                )
                .read()
                .decode()
            )
            assert text.startswith("block_import;thread:")
            doc = json.load(
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/lighthouse/profile"
                )
            )
            names = [p_["name"] for p_ in doc["profiles"]]
            assert "block_import" in names
        # the eager counter moved for the taxonomy root
        assert (
            REGISTRY.counter("profiler_samples_total").value(
                root="block_import"
            )
            > 0
        )
    finally:
        na.stop()
        nb.stop()
        msrv.stop()
        asrv.stop()
        profiler_mod.stop_profiler()
    assert not prof.running


# -- RPC / gossip satellite metrics ------------------------------------------


def test_rpc_latency_histograms_populated():
    from lighthouse_tpu.network import NetworkService
    from lighthouse_tpu.network.rpc import RpcClient

    a = _harness(slots=4)
    na = NetworkService(a.chain, heartbeat_interval=None).start()
    s_status = REGISTRY.histogram("rpc_server_request_seconds_status")
    c_status = REGISTRY.histogram("rpc_client_request_seconds_status")
    s_range = REGISTRY.histogram(
        "rpc_server_request_seconds_beacon_blocks_by_range"
    )
    c_range = REGISTRY.histogram(
        "rpc_client_request_seconds_beacon_blocks_by_range"
    )
    c_md = REGISTRY.histogram("rpc_client_request_seconds_metadata")
    before = (s_status.count, c_status.count, s_range.count, c_range.count,
              c_md.count)
    try:
        b = _harness()
        nb = NetworkService(b.chain, heartbeat_interval=None).start()
        try:
            client = RpcClient("127.0.0.1", na.port)
            client.status(nb.local_status())
            client.metadata()
            blocks = client.blocks_by_range(1, 4, na.decode_block)
            assert len(blocks) == 4
        finally:
            nb.stop()
    finally:
        na.stop()
    after = (s_status.count, c_status.count, s_range.count, c_range.count,
             c_md.count)
    assert all(a_ > b_ for a_, b_ in zip(after, before)), (before, after)


def test_gossipsub_heartbeat_feeds_score_histogram_and_mesh_gauge():
    from lighthouse_tpu.network.gossipsub.behaviour import GossipsubBehaviour

    hist = REGISTRY.histogram("gossipsub_peer_score_distribution")
    before = hist.count
    sent = []
    topic = "/eth2/00000000/beacon_block/ssz_snappy"
    beh = GossipsubBehaviour(
        send=lambda p, f: sent.append(p),
        deliver=lambda t, d, o: True,
        mid_fn=lambda d: d[:20],
        seed=1,
    )
    beh.subscribe(topic)
    for i in range(3):
        beh.add_peer(f"p{i}")
        beh._handle_subscription(f"p{i}", True, topic)
    beh.heartbeat()
    assert hist.count == before + 3  # one observation per peer
    assert REGISTRY.gauge("gossipsub_mesh_peers").value(
        topic="beacon_block"
    ) == len(beh.mesh_peers(topic))
    assert len(beh.mesh_peers(topic)) == 3


# -- bench integration -------------------------------------------------------


def test_bench_compare_regression_sentinel(tmp_path):
    import bench

    def write(path, atts_ms, sync_bps, profiled=False):
        doc = {
            "metric": "bls_batch_verify_1k",
            "value": 1458.0,
            "unit": "sets/sec",
            "vs_baseline": 18.4,
            "details": [
                {
                    "metric": "attestation_batch_ms",
                    "value": atts_ms,
                    "unit": "ms/block",
                    "spread": {
                        "median_s": atts_ms / 1e3,
                        "min_s": atts_ms / 1e3 * 0.98,
                        "max_s": atts_ms / 1e3 * 1.03,
                        "trials": 3,
                    },
                },
                {
                    "metric": "sync_catchup",
                    "value": sync_bps,
                    "unit": "blocks/sec",
                },
            ],
        }
        if profiled:
            doc["profiled"] = True
        path.write_text(json.dumps(doc))
        return str(path)

    old = write(tmp_path / "old.json", 12.7, 148.2)
    # latency +30% → REGRESSED (exit 1); throughput -10% stays ok
    bad = write(tmp_path / "bad.json", 16.6, 133.0)
    ok = write(tmp_path / "ok.json", 13.0, 150.0)
    prof = write(tmp_path / "prof.json", 12.7, 148.2, profiled=True)
    assert bench.compare_runs(old, ok) == 0
    assert bench.compare_runs(old, bad) == 1
    # a throughput COLLAPSE regresses too (direction-aware)
    slow = write(tmp_path / "slow.json", 12.7, 90.0)
    assert bench.compare_runs(old, slow) == 1
    # profiled runs are not comparable
    assert bench.compare_runs(old, prof) == 2
    assert bench.compare_runs(prof, old) == 2


def test_bench_profile_flag_sets_env(monkeypatch):
    import bench

    monkeypatch.delenv("BENCH_PROFILE", raising=False)
    rest = bench._parse_args(["--profile", "--metric", "pairing"])
    assert rest == ["--metric", "pairing"]
    assert __import__("os").environ.get("BENCH_PROFILE") == "1"
    monkeypatch.delenv("BENCH_PROFILE", raising=False)


def test_compile_cache_tracking(tmp_path, monkeypatch):
    from lighthouse_tpu.utils import compile_cache as cc

    hits0, miss0 = cc._HITS.value(), cc._MISSES.value()
    secs0 = cc._COMPILE_SECONDS.value()
    cache = tmp_path / "jc"
    cache.mkdir()
    # no new cache entry → hit
    with cc.track_device_compile("unit_kernel", cache_dir=str(cache)):
        pass
    assert cc._HITS.value() == hits0 + 1
    # cache dir grows inside the block → miss + compile seconds
    with cc.track_device_compile("unit_kernel", cache_dir=str(cache)):
        (cache / "entry").write_text("x")
        time.sleep(0.01)
    assert cc._MISSES.value() == miss0 + 1
    assert cc._COMPILE_SECONDS.value() > secs0
    stats = cc.compile_cache_stats()
    assert {"hits", "misses", "compile_seconds"} <= set(stats)
    # the warmup rode a device_compile span (standard metrics path)
    assert REGISTRY.histogram("trace_span_seconds_device_compile").count >= 2


# -- overhead guard ----------------------------------------------------------


@pytest.mark.perf_smoke
def test_sampled_block_import_overhead_bounded():
    """Acceptance bound: block import with the sampler running at the
    default rate stays within 1.10× of unsampled (plus a 10 ms absolute
    floor for timer noise on single-digit-ms minimal-preset imports)."""
    import statistics

    def run_mode(profiler):
        h = _harness()
        if profiler is not None:
            profiler.start()
        try:
            times = []
            for _ in range(8):
                slot = h.chain.head_state.slot + 1
                t0 = time.perf_counter()
                h.add_block_at_slot(slot)
                times.append(time.perf_counter() - t0)
            return statistics.median(times)
        finally:
            if profiler is not None:
                profiler.stop()

    off = run_mode(None)
    on = run_mode(StackProfiler())  # default ~59 Hz
    assert on <= off * 1.10 + 0.010, (
        f"sampling overhead out of bounds: on={on * 1000:.2f}ms "
        f"off={off * 1000:.2f}ms"
    )


def test_bench_compare_direction_probe():
    """Unit-string direction detection: every throughput unit in the
    suite must read higher-is-better — testnet_soak's "per wall-second"
    phrasing once read as a latency, flagging a +25% improvement as
    REGRESSED — and the "/s " probe must not catch "ms/…" latencies."""
    import bench

    for unit in (
        "sets/sec",
        "leaves/sec",
        "blocks/sec (two-node loopback catch-up, batch state machine)",
        "cells/s (batched RLC lane)",
        "slots finalized per wall-second (5-node fleet, healthy soak)",
        "req/sec (hot-cache full-table validators at 1000000 validators)",
    ):
        assert bench._higher_is_better(unit), unit
    for unit in (
        "ms/block (produce+sign+import)",
        "ms/block (pre-advanced, epoch boundary, 1M validators)",
        "ms/epoch (1000000 validators, minimal preset)",
        "s/cold columnar build",
        "s heal->finality (after >=50% recovery import)",
        "",
        None,
    ):
        assert not bench._higher_is_better(unit), unit
