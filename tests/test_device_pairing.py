"""Device pairing stack vs the host bigint oracle.

Covers the full device BLS chain (VERDICT r2 task #1): Fq12 tower ops,
ψ-ladder subgroup checks, Budroni–Pintore cofactor clearing, staged SSWU
hash-to-G2, the batched Miller loop + shared-final-exponentiation
multi-pairing check, and the end-to-end device batch verifier. Everything
is `slow` — first XLA-CPU compiles take minutes; the repo-local persistent
cache amortizes them across runs."""

import hashlib
import random

import jax.numpy as jnp
import numpy as np
import pytest

from lighthouse_tpu.crypto.bls12_381 import (
    FQ,
    FQ2,
    G1_GEN,
    G2_GEN,
    g2_in_subgroup,
    hash_to_g2,
    pt_eq,
    pt_mul,
    to_affine,
)
from lighthouse_tpu.crypto.bls12_381 import fields as HF
from lighthouse_tpu.crypto.bls12_381.curve import g2_clear_cofactor, pt_neg
from lighthouse_tpu.crypto.bls12_381.fields import P, f2, f2_add, f2_mul, f2_sqrt
from lighthouse_tpu.ops import bls381_htc as H
from lighthouse_tpu.ops import bls381_pairing as DP
from lighthouse_tpu.ops import bls381_tower as TW
from lighthouse_tpu.ops.bls381 import g2_points_from_device
from lighthouse_tpu.ops.bls381_tower import fq2_const

# every test in this file is tier-2: pairing kernels: the slowest compiles in the tree.
# tests/conftest.py enforces this marker at collection time.
pytestmark = pytest.mark.slow

rng = random.Random(21)


def _rand_f12():
    def rf2():
        return (rng.randrange(P), rng.randrange(P))

    def rf6():
        return (rf2(), rf2(), rf2())

    return (rf6(), rf6())


def _non_subgroup_g2():
    x = f2(3, 1)
    while True:
        rhs = f2_add(f2_mul(f2_mul(x, x), x), (4, 4))
        y = f2_sqrt(rhs)
        if y is not None and not g2_in_subgroup((x, y, f2(1))):
            return (x, y)
        x = f2_add(x, f2(1))


def test_f12_tower_ops_vs_host():
    a12 = [_rand_f12() for _ in range(4)]
    b12 = [_rand_f12() for _ in range(4)]
    da = jnp.asarray(TW.f12_to_device(a12))
    db = jnp.asarray(TW.f12_to_device(b12))
    assert TW.f12_from_device(TW.f12_mul(da, db)) == [
        HF.f12_mul(x, y) for x, y in zip(a12, b12)
    ]
    assert TW.f12_from_device(TW.f12_sqr(da)) == [HF.f12_sqr(x) for x in a12]
    assert TW.f12_from_device(TW.f12_inv(da)) == [HF.f12_inv(x) for x in a12]
    assert TW.f12_from_device(TW.f12_frob(da)) == [HF.f12_frob(x) for x in a12]


@pytest.mark.slow
def test_f2_sqrt_device():
    sq_in = []
    for _ in range(6):
        v = (rng.randrange(P), rng.randrange(P))
        sq_in.append(HF.f2_sqr(v))
    sq_in.append((4, 0))  # y == 0 path
    x = 5
    while HF.f2_legendre((x, 3)) >= 0:
        x += 1
    sq_in.append((x, 3))  # non-square
    dev = jnp.asarray(np.stack([fq2_const(v) for v in sq_in]))
    roots, is_sq = H.f2_sqrt_device(dev)
    assert np.asarray(is_sq).tolist() == [True] * 7 + [False]
    got_sq = np.asarray(TW.f2_sqr(roots))
    assert (got_sq[:7] == np.asarray(dev)[:7]).all()


@pytest.mark.slow
def test_g2_subgroup_check_device():
    good = [pt_mul(FQ2, G2_GEN, k) for k in (1, 5, 123456789)]
    bad = _non_subgroup_g2()
    pts = [to_affine(FQ2, p) for p in good] + [bad]
    qx, qy, q_inf = DP.g2_affine_to_device(pts)
    res = np.asarray(DP.g2_subgroup_check_device(qx, qy, q_inf))
    assert res.tolist() == [True, True, True, False]


@pytest.mark.slow
def test_g2_clear_cofactor_device_vs_host():
    bad = _non_subgroup_g2()
    qx, qy, _ = DP.g2_affine_to_device([bad])
    out = DP.g2_clear_cofactor_device((qx, qy, DP._one_fq2((1,))))
    got = g2_points_from_device(out)[0]
    want = g2_clear_cofactor((bad[0], bad[1], f2(1)))
    assert pt_eq(FQ2, got, want)
    assert g2_in_subgroup(got)


@pytest.mark.slow
def test_hash_to_g2_device_vs_host():
    msgs = [hashlib.sha256(bytes([i])).digest() for i in range(4)]
    u = H.messages_to_field_device(msgs)
    got = g2_points_from_device(H.hash_to_g2_device(jnp.asarray(u)))
    for m, g in zip(msgs, got):
        assert pt_eq(FQ2, g, hash_to_g2(m))


@pytest.mark.slow
def test_multi_pairing_check_device():
    a = 987654321
    pa = pt_mul(FQ, G1_GEN, a)
    qa = pt_mul(FQ2, G2_GEN, a)
    xp, yp, p_inf = DP.g1_affine_to_device(
        [to_affine(FQ, pt_neg(FQ, pa)), to_affine(FQ, G1_GEN)]
    )
    qx, qy, q_inf = DP.g2_affine_to_device(
        [to_affine(FQ2, G2_GEN), to_affine(FQ2, qa)]
    )
    assert bool(DP.multi_pairing_check_device(xp, yp, p_inf, qx, qy, q_inf))
    xp2, yp2, p_inf2 = DP.g1_affine_to_device(
        [to_affine(FQ, pt_neg(FQ, pt_mul(FQ, G1_GEN, a + 1))), to_affine(FQ, G1_GEN)]
    )
    assert not bool(
        DP.multi_pairing_check_device(xp2, yp2, p_inf2, qx, qy, q_inf)
    )


@pytest.mark.slow
def test_full_device_batch_verify():
    from lighthouse_tpu.crypto import bls
    from lighthouse_tpu.crypto.bls import AggregateSignature
    from lighthouse_tpu.ops.bls381_verify import verify_signature_sets_device_full

    bls.set_backend("host")
    try:
        kps = bls.interop_keypairs(8)
        msg = hashlib.sha256(b"full device").digest()
        sets = []
        for i, kp in enumerate(kps):
            m = hashlib.sha256(bytes([i])).digest()
            sets.append(bls.SignatureSet.single(kp.sk.sign(m), kp.pk, m))
        aggsig = AggregateSignature.from_signatures(
            [kp.sk.sign(msg) for kp in kps[:3]]
        ).to_signature()
        sets.append(bls.SignatureSet(aggsig, [kp.pk for kp in kps[:3]], msg))
        assert verify_signature_sets_device_full(sets, random.Random(5))
        bad = list(sets)
        bad[2] = bls.SignatureSet.single(
            sets[3].signature, sets[2].pubkeys[0], sets[2].message
        )
        assert not verify_signature_sets_device_full(bad, random.Random(6))
    finally:
        bls.set_backend("fake_crypto")
