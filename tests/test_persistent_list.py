"""PersistentList: structural sharing + internal hash caching.

The milhouse analog (reference consensus/types/src/beacon_state.rs:34,371
stores validators/balances as structurally-shared hash-caching lists)."""

import time

import pytest

from lighthouse_tpu.crypto import bls
from lighthouse_tpu.ssz.core import List, uint64
from lighthouse_tpu.ssz.persistent import BLOCK_ELEMS, PersistentList


def test_list_surface_matches_plain_list():
    vals = list(range(10_000))
    p = PersistentList(vals)
    assert len(p) == 10_000
    assert p[0] == 0 and p[9_999] == 9_999 and p[-1] == 9_999
    assert list(p) == vals
    assert p == vals
    p[5] = 42
    assert p[5] == 42
    p.append(77)
    assert len(p) == 10_001 and p[-1] == 77
    assert p[100:103] == [100, 101, 102]
    with pytest.raises(IndexError):
        p[10_001]
    with pytest.raises(ValueError):
        p[0] = -1


def test_copy_shares_blocks_and_cow_isolates():
    p = PersistentList(range(3 * BLOCK_ELEMS))
    c = p.copy()
    assert p.shared_block_count(c) == 3
    c[0] = 999  # clones only block 0 of the copy
    assert p[0] == 0 and c[0] == 999
    assert p.shared_block_count(c) == 2
    # mutating the ORIGINAL after copy must not leak into the copy either
    p[2 * BLOCK_ELEMS] = 123
    assert c[2 * BLOCK_ELEMS] == 2 * BLOCK_ELEMS
    assert p.shared_block_count(c) == 1


def test_hash_tree_root_matches_reference_merkleization():
    T = List[uint64, 1 << 40]
    for n in (0, 1, 5, BLOCK_ELEMS, BLOCK_ELEMS + 3, 2 * BLOCK_ELEMS + 17):
        vals = [(i * 7919) % (1 << 60) for i in range(n)]
        p = PersistentList(vals)
        assert T.hash_tree_root_of(p) == T.hash_tree_root_of(vals), n


def test_coerce_never_aliases_the_source():
    """Building a container field from an existing PersistentList must
    insert a CoW barrier — mutating the source afterwards cannot leak."""
    T = List[uint64, 1 << 20]
    src = PersistentList([1, 2, 3])
    field_val = T.coerce(src)
    assert field_val is not src
    src[0] = 99
    assert field_val[0] == 1


def test_hash_tree_root_small_limit_types():
    """Lists whose chunk limit is below one block (e.g. attesting-indices
    shapes) must still produce the exact SSZ root — regression for the
    depth-clamping bug."""
    for limit in (8, 64, 2048, 16384):
        T = List[uint64, limit * 4]  # limit*4 elems = `limit` chunks
        for n in (0, 1, 3, 7):
            vals = list(range(100, 100 + n))
            assert T.hash_tree_root_of(PersistentList(vals)) == T.hash_tree_root_of(
                vals
            ), (limit, n)


def test_hash_cache_reuse_across_copies():
    T = List[uint64, 1 << 40]
    n = 64 * BLOCK_ELEMS  # 262k elements
    p = PersistentList(range(n))
    t0 = time.perf_counter()
    r1 = T.hash_tree_root_of(p)
    cold = time.perf_counter() - t0

    c = p.copy()
    c[0] = 1  # dirty exactly one block
    t0 = time.perf_counter()
    r2 = T.hash_tree_root_of(c)
    warm = time.perf_counter() - t0
    assert r2 != r1
    assert T.hash_tree_root_of(c) == T.hash_tree_root_of(list(c))
    # one dirty block out of 64: the memoized rebuild must be much
    # cheaper than the cold full build (conservative 5x bound)
    assert warm < cold / 5, (cold, warm)
    # and the ORIGINAL's memos survived its copy untouched
    t0 = time.perf_counter()
    assert T.hash_tree_root_of(p) == r1
    assert time.perf_counter() - t0 < cold / 20


def test_slice_assign_preserves_untouched_block_memos():
    T = List[uint64, 1 << 40]
    n = 8 * BLOCK_ELEMS
    p = PersistentList([5] * n)
    T.hash_tree_root_of(p)  # build memos
    new = [5] * n
    new[3 * BLOCK_ELEMS + 1] = 6  # change lands in block 3 only
    p[:] = new
    dirty = [i for i, b in enumerate(p._blocks) if b.root is None]
    assert dirty == [3]
    assert T.hash_tree_root_of(p) == T.hash_tree_root_of(new)


def test_chain_states_share_balance_blocks_across_copies():
    """End-to-end: a harness chain's states carry PersistentList balances
    and copies share blocks (the tree-states capability on the real
    BeaconState path)."""
    from lighthouse_tpu.beacon_chain.harness import BeaconChainHarness
    from lighthouse_tpu.types.chain_spec import minimal_spec
    from lighthouse_tpu.types.eth_spec import MinimalEthSpec as E

    prev = bls.backend_name()
    bls.set_backend("fake_crypto")
    try:
        h = BeaconChainHarness(minimal_spec(), E, validator_count=16)
        assert isinstance(h.chain.head_state.balances, PersistentList)
        h.extend_chain(E.SLOTS_PER_EPOCH + 2)
        assert isinstance(h.chain.head_state.balances, PersistentList)
        # serialization still round-trips through the plain SSZ path
        st = h.chain.head_state
        data = st.serialize()
        rt = type(st).deserialize(data)
        assert list(rt.balances) == list(st.balances)
        assert rt.hash_tree_root() == st.hash_tree_root()
    finally:
        bls.set_backend(prev)


# ---------------------------------------------------------------------------
# PersistentContainerList (the milhouse List<Validator> analog)
# ---------------------------------------------------------------------------


def _mkvalidators(n, tag=0):
    from lighthouse_tpu.types.containers import build_types
    from lighthouse_tpu.types.eth_spec import MinimalEthSpec as E

    V = build_types(E).Validator
    return V, [
        V(
            pubkey=bytes([i % 251, tag % 251]) + b"\x00" * 46,
            withdrawal_credentials=(i * 7 + tag).to_bytes(32, "little"),
            effective_balance=32_000_000_000 + i,
            slashed=(i % 5 == 0),
            activation_eligibility_epoch=i,
            activation_epoch=i + 1,
            exit_epoch=2**64 - 1,
            withdrawable_epoch=2**64 - 1,
        )
        for i in range(n)
    ]


def test_container_list_root_matches_plain_path():
    from lighthouse_tpu.ssz.persistent import (
        CONTAINER_BLOCK,
        PersistentContainerList,
    )
    from lighthouse_tpu.types.eth_spec import MinimalEthSpec as E

    for n in (0, 1, 5, CONTAINER_BLOCK, CONTAINER_BLOCK * 3 + 17):
        V, vals = _mkvalidators(n)
        T = List[V, E.VALIDATOR_REGISTRY_LIMIT]
        p = PersistentContainerList(vals, elem_t=V)
        assert T.hash_tree_root_of(p) == T.hash_tree_root_of(vals), n


def test_container_list_bulk_build_matches_per_element():
    """The columnar cold path writes the same memos the per-element path
    would (validator-shaped containers)."""
    from lighthouse_tpu.ssz.persistent import (
        PersistentContainerList,
        bulk_container_roots,
    )

    V, vals = _mkvalidators(700, tag=3)
    bulk_container_roots(vals)
    for v in vals:
        want = type(v).hash_tree_root_of(v)
        assert v.__dict__["_thc_root"] == want


def test_container_list_copy_isolation_and_sharing():
    from lighthouse_tpu.ssz.persistent import (
        CONTAINER_BLOCK,
        PersistentContainerList,
    )
    from lighthouse_tpu.types.eth_spec import MinimalEthSpec as E

    V, vals = _mkvalidators(CONTAINER_BLOCK * 4)
    T = List[V, E.VALIDATOR_REGISTRY_LIMIT]
    a = PersistentContainerList(vals, elem_t=V)
    root_a = T.hash_tree_root_of(a)
    b = a.copy()
    assert a.shared_block_count(b) == 4
    # copy-on-write mutation through mutate() touches one block only
    v = b.mutate(CONTAINER_BLOCK + 3)
    v.effective_balance = 1
    assert a.shared_block_count(b) == 3
    assert T.hash_tree_root_of(a) == root_a  # sibling untouched
    assert T.hash_tree_root_of(b) != root_a
    # plain-list recompute agrees with the incremental answer
    assert T.hash_tree_root_of(b) == T.hash_tree_root_of(list(b))


def test_chain_states_share_validator_blocks_and_roundtrip():
    """End-to-end: chain states carry a PersistentContainerList registry;
    epoch processing (registry updates, slashings, effective balances)
    mutates via the CoW discipline, and roots match the plain SSZ path."""
    from lighthouse_tpu.beacon_chain.harness import BeaconChainHarness
    from lighthouse_tpu.ssz.persistent import PersistentContainerList
    from lighthouse_tpu.types.chain_spec import minimal_spec
    from lighthouse_tpu.types.eth_spec import MinimalEthSpec as E

    prev = bls.backend_name()
    bls.set_backend("fake_crypto")
    try:
        h = BeaconChainHarness(minimal_spec(), E, validator_count=16)
        assert isinstance(
            h.chain.head_state.validators, PersistentContainerList
        )
        h.extend_chain(2 * E.SLOTS_PER_EPOCH + 2)
        st = h.chain.head_state
        data = st.serialize()
        rt = type(st).deserialize(data)
        assert [v.hash_tree_root() for v in rt.validators] == [
            v.hash_tree_root() for v in st.validators
        ]
        assert rt.hash_tree_root() == st.hash_tree_root()
    finally:
        bls.set_backend(prev)


# --- dirty-index propagation (the token protocol feeding the hash caches) ---


def test_dirty_tracking_marks_and_drains():
    from lighthouse_tpu.ssz.persistent import PersistentList

    lst = PersistentList(range(100))
    t0 = lst.dirt_token
    base, dirty = lst.drain_dirty()
    assert base is t0 and dirty == set()
    assert lst.dirt_token is not t0  # drain advances the baseline

    lst[7] = 99
    lst[7] = 99  # no-op write: not dirty
    lst[12] = 1
    lst.append(5)
    base, dirty = lst.drain_dirty()
    assert dirty == {7, 12, 100}
    base, dirty = lst.drain_dirty()
    assert dirty == set()


def test_dirty_tracking_overflow_degrades_to_all():
    from lighthouse_tpu.ssz.persistent import _DIRTY_CAP, PersistentList

    lst = PersistentList(range(_DIRTY_CAP + 10))
    lst.drain_dirty()
    lst[:] = [v + 1 for v in lst]  # mass churn beyond the cap
    base, dirty = lst.drain_dirty()
    assert dirty is None  # "everything may have changed"


def test_dirty_baseline_tokens_cannot_collide_across_branches():
    """The hazard the token protocol exists for: two copies diverge, each
    gets drained by its own consumer — the post-drain tokens must differ,
    so a cache that committed branch A can never accept branch B's dirt
    as an exact delta."""
    from lighthouse_tpu.ssz.persistent import PersistentList

    orig = PersistentList(range(50))
    a = orig.copy()
    b = orig.copy()
    assert a.dirt_token is b.dirt_token  # shared baseline at copy time
    a[3] = 111
    b[9] = 222
    base_a, dirty_a = a.drain_dirty()
    base_b, dirty_b = b.drain_dirty()
    assert base_a is base_b  # same baseline...
    assert dirty_a == {3} and dirty_b == {9}  # ...different exact deltas
    assert a.dirt_token is not b.dirt_token  # post-drain: distinct lineages


def test_copy_carries_pending_dirt():
    """Mutations made before a copy() belong to BOTH sides: each side's
    cache (sharing committed layers) needs them."""
    from lighthouse_tpu.ssz.persistent import PersistentContainerList

    _, vals = _mkvalidators(10)
    lst = PersistentContainerList(vals)
    lst.drain_dirty()
    lst.mutate(4).effective_balance = 7
    dup = lst.copy()
    _, dirty_dup = dup.drain_dirty()
    _, dirty_orig = lst.drain_dirty()
    assert dirty_dup == {4} and dirty_orig == {4}


def test_wholesale_rebuild_resets_baseline():
    from lighthouse_tpu.ssz.persistent import PersistentList

    lst = PersistentList(range(20))
    t0 = lst.dirt_token
    lst[::2] = [0] * 10  # stepped slice: the wholesale-rebuild path
    assert lst.dirt_token is not t0  # fresh baseline: consumers full-diff
    _, dirty = lst.drain_dirty()
    assert dirty == set()


def test_stale_mutate_handle_raises_after_root_commit():
    """A mutate() handle kept across a root commit must not be silently
    writable: its writes would be invisible to the drained dirty delta
    and the committed root would diverge. The drain re-freezes handles,
    so the stale write raises and the caller re-mutates."""
    import pytest as _pytest

    from lighthouse_tpu.ssz.core import FrozenElementError
    from lighthouse_tpu.ssz.persistent import PersistentContainerList

    _, vals = _mkvalidators(10)
    lst = PersistentContainerList(vals)
    v = lst.mutate(4)
    v.effective_balance = 7
    lst.drain_dirty()  # a cache committed a root over current contents
    with _pytest.raises(FrozenElementError):
        v.effective_balance = 9  # stale handle: must not corrupt silently
    w = lst.mutate(4)  # the sanctioned path still works
    w.effective_balance = 9
    _, dirty = lst.drain_dirty()
    assert dirty == {4}
