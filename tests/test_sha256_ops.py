"""Device SHA-256 kernel vs hashlib."""

import hashlib

import numpy as np

from lighthouse_tpu.ops.sha256 import (
    bytes_to_words,
    merkleize_device,
    sha256_pairs,
    words_to_bytes,
)
from lighthouse_tpu.utils.hash import ZERO_HASHES, hash32_concat


def test_sha256_pairs_matches_hashlib():
    rng = np.random.default_rng(0)
    msgs = [rng.integers(0, 256, 64, dtype=np.uint8).tobytes() for _ in range(33)]
    blocks = bytes_to_words(b"".join(msgs)).reshape(-1, 16)
    out = np.asarray(sha256_pairs(blocks))
    for i, m in enumerate(msgs):
        assert words_to_bytes(out[i]) == hashlib.sha256(m).digest()


def test_zero_hashes_on_device():
    leaves = np.zeros((8, 8), dtype=np.uint32)
    root = words_to_bytes(merkleize_device(leaves))
    assert root == ZERO_HASHES[3]


def test_merkleize_device_matches_host():
    rng = np.random.default_rng(1)
    data = rng.integers(0, 256, 32 * 16, dtype=np.uint8).tobytes()
    nodes = [data[i : i + 32] for i in range(0, len(data), 32)]
    while len(nodes) > 1:
        nodes = [hash32_concat(nodes[i], nodes[i + 1]) for i in range(0, len(nodes), 2)]
    got = words_to_bytes(merkleize_device(bytes_to_words(data)))
    assert got == nodes[0]
