"""watch analytics: updater fills the DB from a live node over HTTP."""

import json
from dataclasses import replace

from lighthouse_tpu.beacon_chain.harness import BeaconChainHarness
from lighthouse_tpu.crypto import bls
from lighthouse_tpu.eth2 import BeaconNodeHttpClient
from lighthouse_tpu.http_api import HttpApiServer
from lighthouse_tpu.types.chain_spec import minimal_spec
from lighthouse_tpu.types.containers import build_types
from lighthouse_tpu.types.eth_spec import MinimalEthSpec as E
from lighthouse_tpu.watch import WatchDB, WatchUpdater


def test_watch_updater_records_chain():
    bls.set_backend("fake_crypto")
    spec = replace(minimal_spec(), altair_fork_epoch=0)
    h = BeaconChainHarness(spec, E, validator_count=16)
    h.extend_chain(2 * E.SLOTS_PER_EPOCH)
    server = HttpApiServer(h.chain).start()
    try:
        client = BeaconNodeHttpClient(f"http://127.0.0.1:{server.port}")
        db = WatchDB()
        updater = WatchUpdater(client, db, build_types(E))
        n = updater.update()
        assert n == 2 * E.SLOTS_PER_EPOCH  # slots 1..16 (no skips)
        counts = db.proposer_counts()
        assert sum(counts.values()) == 2 * E.SLOTS_PER_EPOCH
        assert db.missed_slots() == []
        just, fin = db.latest_finality()
        assert just >= 0 and fin >= 0
        # idempotent second run records nothing new
        assert updater.update() == 0

        # a skipped slot shows up as missed
        skip_to = h.chain.head_state.slot + 2
        h.slot_clock.set_slot(skip_to)
        h.add_block_at_slot(skip_to)
        assert updater.update() == 2
        assert db.missed_slots() == [skip_to - 1]
    finally:
        server.stop()


def test_watch_packing_and_rest_server():
    """Block-packing analytics + the watch REST surface (server.rs)."""
    import urllib.request

    from lighthouse_tpu.watch import WatchDB, WatchServer, WatchUpdater

    bls.set_backend("fake_crypto")
    spec = replace(minimal_spec(), altair_fork_epoch=0)
    h = BeaconChainHarness(spec, E, validator_count=16)
    h.extend_chain(E.SLOTS_PER_EPOCH + 2)
    server = HttpApiServer(h.chain).start()
    try:
        client = BeaconNodeHttpClient(f"http://127.0.0.1:{server.port}")
        db = WatchDB()
        WatchUpdater(client, db, build_types(E)).update()
        stats = db.packing_stats()
        assert stats["blocks"] == E.SLOTS_PER_EPOCH + 2
        assert stats["avg_attestations"] > 0  # harness attests each slot
        assert 0 < stats["avg_sync_participation"] <= 1.0

        ws = WatchServer(db).start()
        try:
            base = f"http://127.0.0.1:{ws.port}"
            packing = json.loads(
                urllib.request.urlopen(f"{base}/v1/packing", timeout=5).read()
            )
            assert packing["blocks"] == stats["blocks"]
            proposers = json.loads(
                urllib.request.urlopen(f"{base}/v1/proposers", timeout=5).read()
            )
            assert sum(proposers.values()) == E.SLOTS_PER_EPOCH + 2
            missed = json.loads(
                urllib.request.urlopen(
                    f"{base}/v1/slots/missed", timeout=5
                ).read()
            )
            assert missed == []
            # per-block rewards pulled from the node's rewards route
            rewards = json.loads(
                urllib.request.urlopen(f"{base}/v1/rewards", timeout=5).read()
            )
            # every Altair block must yield rewards — a silent fetch hole
            # would show here as a short count
            assert rewards["blocks"] == E.SLOTS_PER_EPOCH + 2
            assert rewards["total_gwei"] > 0
            assert sum(rewards["per_proposer"].values()) == rewards["total_gwei"]
            bp = json.loads(
                urllib.request.urlopen(
                    f"{base}/v1/blockprint", timeout=5
                ).read()
            )
            assert sum(bp.values()) == E.SLOTS_PER_EPOCH + 2
        finally:
            ws.stop()
    finally:
        server.stop()


def test_blockprint_classification_and_aggregate():
    """Client fingerprints from graffiti/extra_data feed the blockprint
    table and the /v1/blockprint share aggregate (watch/src/blockprint
    analog)."""
    from lighthouse_tpu.watch import WatchDB
    from lighthouse_tpu.watch.blockprint import classify_block
    from lighthouse_tpu.types.containers import build_types
    from lighthouse_tpu.types.eth_spec import MinimalEthSpec

    t = build_types(MinimalEthSpec)

    def block(graffiti=b"", slot=1):
        body = t.BeaconBlockBody(graffiti=graffiti.ljust(32, b"\x00"))
        return t.SignedBeaconBlock(
            message=t.BeaconBlock(slot=slot, body=body),
            signature=b"\x00" * 96,
        )

    assert classify_block(block(b"Lighthouse/v4.6.0"))["best_guess"] == "Lighthouse"
    assert classify_block(block(b"teku/23.1"))["best_guess"] == "Teku"
    assert classify_block(block(b"prysm-rc"))["best_guess"] == "Prysm"
    got = classify_block(block(b"gm"))
    assert got["best_guess"] == "Unknown"
    assert got["graffiti"] == "gm"

    # post-merge: the payload's extra_data identifies the EL
    bellatrix_body = t.BeaconBlockBodyBellatrix(
        graffiti=b"Nimbus".ljust(32, b"\x00"),
        execution_payload=t.ExecutionPayload(extra_data=b"geth go1.21"),
    )
    signed = t.SignedBeaconBlockBellatrix(
        message=t.BeaconBlockBellatrix(slot=2, body=bellatrix_body),
        signature=b"\x00" * 96,
    )
    p = classify_block(signed)
    assert p["best_guess"] == "Nimbus" and p["el_guess"] == "Geth"

    db = WatchDB()
    db.record_blockprint(1, classify_block(block(b"Lighthouse", slot=1)))
    db.record_blockprint(2, p)
    db.record_blockprint(3, classify_block(block(b"Lighthouse", slot=3)))
    assert db.blockprint_shares() == {"Lighthouse": 2, "Nimbus": 1}
    assert db.blockprint_for_slot(2)["el_guess"] == "Geth"
    assert db.blockprint_for_slot(99) is None
