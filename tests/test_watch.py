"""watch analytics: updater fills the DB from a live node over HTTP."""

from dataclasses import replace

from lighthouse_tpu.beacon_chain.harness import BeaconChainHarness
from lighthouse_tpu.crypto import bls
from lighthouse_tpu.eth2 import BeaconNodeHttpClient
from lighthouse_tpu.http_api import HttpApiServer
from lighthouse_tpu.types.chain_spec import minimal_spec
from lighthouse_tpu.types.containers import build_types
from lighthouse_tpu.types.eth_spec import MinimalEthSpec as E
from lighthouse_tpu.watch import WatchDB, WatchUpdater


def test_watch_updater_records_chain():
    bls.set_backend("fake_crypto")
    spec = replace(minimal_spec(), altair_fork_epoch=0)
    h = BeaconChainHarness(spec, E, validator_count=16)
    h.extend_chain(2 * E.SLOTS_PER_EPOCH)
    server = HttpApiServer(h.chain).start()
    try:
        client = BeaconNodeHttpClient(f"http://127.0.0.1:{server.port}")
        db = WatchDB()
        updater = WatchUpdater(client, db, build_types(E))
        n = updater.update()
        assert n == 2 * E.SLOTS_PER_EPOCH  # slots 1..16 (no skips)
        counts = db.proposer_counts()
        assert sum(counts.values()) == 2 * E.SLOTS_PER_EPOCH
        assert db.missed_slots() == []
        just, fin = db.latest_finality()
        assert just >= 0 and fin >= 0
        # idempotent second run records nothing new
        assert updater.update() == 0

        # a skipped slot shows up as missed
        skip_to = h.chain.head_state.slot + 2
        h.slot_clock.set_slot(skip_to)
        h.add_block_at_slot(skip_to)
        assert updater.update() == 2
        assert db.missed_slots() == [skip_to - 1]
    finally:
        server.stop()
