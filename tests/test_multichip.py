"""Multi-device sharding tests on the 8-device virtual CPU mesh.

conftest.py provisions 8 virtual CPU devices via
--xla_force_host_platform_device_count, the same mechanism the driver's
dryrun uses (SURVEY.md §2.9: ICI batch sharding is the TPU-native analog of
the reference's rayon batch parallelism)."""

import hashlib

import jax
import numpy as np
import pytest

from lighthouse_tpu.ops.merkle_sharded import build_sharded_merkle
from lighthouse_tpu.ops.sha256 import bytes_to_words, words_to_bytes

# every test in this file is tier-2: 8-device mesh kernels: slow XLA-CPU compiles.
# tests/conftest.py enforces this marker at collection time.
pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def eight_devices():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    return jax.devices()[:8]


def _host_merkle_root(data: bytes) -> bytes:
    nodes = [data[i : i + 32] for i in range(0, len(data), 32)]
    while len(nodes) > 1:
        nodes = [
            hashlib.sha256(nodes[i] + nodes[i + 1]).digest()
            for i in range(0, len(nodes), 2)
        ]
    return nodes[0]


def test_sharded_merkle_root_matches_host(eight_devices):
    n_devices, per_device = 8, 16
    mesh, fn, sharding = build_sharded_merkle(n_devices, per_device)
    rng = np.random.default_rng(3)
    data = rng.integers(
        0, 256, size=n_devices * per_device * 32, dtype=np.uint8
    ).tobytes()
    leaves = jax.device_put(bytes_to_words(data), sharding)
    root = words_to_bytes(fn(leaves))
    assert root == _host_merkle_root(data)


def test_sharded_merkle_input_actually_sharded(eight_devices):
    n_devices, per_device = 8, 8
    mesh, fn, sharding = build_sharded_merkle(n_devices, per_device)
    rng = np.random.default_rng(4)
    data = rng.integers(
        0, 256, size=n_devices * per_device * 32, dtype=np.uint8
    ).tobytes()
    leaves = jax.device_put(bytes_to_words(data), sharding)
    # the leaf buffer must be split over all 8 devices, not replicated
    assert len(leaves.sharding.device_set) == n_devices


def test_dryrun_multichip_entrypoint():
    """The driver-facing entry must be green end-to-end (VERDICT r1 weak #1)."""
    import sys

    sys.path.insert(0, "/root/repo")
    from __graft_entry__ import dryrun_multichip

    dryrun_multichip(8)


@pytest.mark.slow
def test_sharded_rlc_bls_matches_host(eight_devices):
    """The sharded BLS batch step (per-device RLC scalar-mul shards + ICI
    point-sum reduction) matches the host bigint oracle — the multichip
    half of batch signature verification (SURVEY §2.9)."""
    from lighthouse_tpu.ops.bls381_sharded import build_sharded_bls, dryrun_sharded_bls

    mesh, fn, sharding = build_sharded_bls(8)
    dryrun_sharded_bls(mesh)  # asserts vs host internally
