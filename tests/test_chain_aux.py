"""Beacon-chain auxiliary subsystems.

SSE events (events.rs), validator monitor (validator_monitor.rs),
block-times cache (block_times_cache.rs), state-advance pre-compute
(state_advance_timer.rs:1-15), and fork revert (fork_revert.rs:25)."""

import urllib.request

import pytest

from lighthouse_tpu.beacon_chain.events import (
    TOPIC_BLOCK,
    TOPIC_FINALIZED,
    TOPIC_HEAD,
)
from lighthouse_tpu.beacon_chain.fork_revert import revert_to_fork_boundary
from lighthouse_tpu.beacon_chain.harness import BeaconChainHarness
from lighthouse_tpu.beacon_chain.state_advance import StateAdvanceTimer
from lighthouse_tpu.crypto import bls
from lighthouse_tpu.types.chain_spec import minimal_spec
from lighthouse_tpu.types.eth_spec import MinimalEthSpec

E = MinimalEthSpec


@pytest.fixture(autouse=True)
def _fake_crypto():
    prev = bls.backend_name()
    bls.set_backend("fake_crypto")
    yield
    bls.set_backend(prev)


def _harness(n=16):
    return BeaconChainHarness(minimal_spec(), E, validator_count=n)


def test_sse_events_block_head_finalized():
    h = _harness()
    sub = h.chain.event_handler.subscribe([TOPIC_BLOCK, TOPIC_HEAD, TOPIC_FINALIZED])
    h.extend_chain(4 * E.SLOTS_PER_EPOCH)
    # delivery rides the broadcast thread: flush() is the happens-before
    # edge between publishing and draining
    assert h.chain.event_handler.flush(10.0)
    events = sub.drain()
    topics = [e["topic"] for e in events]
    assert topics.count(TOPIC_BLOCK) == 4 * E.SLOTS_PER_EPOCH
    assert TOPIC_HEAD in topics
    assert TOPIC_FINALIZED in topics  # chain finalized within 4 epochs
    blk = next(e for e in events if e["topic"] == TOPIC_BLOCK)
    assert blk["data"]["slot"] == "1"
    assert blk["data"]["block"].startswith("0x")
    # subscription filtering: unknown topic rejected
    with pytest.raises(ValueError):
        h.chain.event_handler.subscribe(["nope"])


def test_sse_http_route_streams_frames():
    h = _harness()
    from lighthouse_tpu.http_api import HttpApiServer

    srv = HttpApiServer(h.chain).start()
    try:
        h.extend_chain(2)
        url = (
            f"http://127.0.0.1:{srv.port}/eth/v1/events"
            "?topics=block&max_seconds=1"
        )
        # events emitted after subscription: extend while the request is open
        import threading

        body_holder = {}

        def read():
            with urllib.request.urlopen(url, timeout=10) as r:
                body_holder["ct"] = r.headers["Content-Type"]
                body_holder["body"] = r.read().decode()

        t = threading.Thread(target=read)
        t.start()
        import time

        time.sleep(0.3)
        h.extend_chain(2)
        t.join(timeout=10)
        assert body_holder["ct"] == "text/event-stream"
        assert "event: block" in body_holder["body"]
        assert '"slot"' in body_holder["body"]
    finally:
        srv.stop()


def test_validator_monitor_hits_and_misses():
    h = _harness()
    mon = h.chain.validator_monitor
    mon.add_validator(0)
    mon.add_validator(5)
    h.extend_chain(3 * E.SLOTS_PER_EPOCH)
    v0 = mon.summary(0)
    assert v0.attestations_included >= 2
    assert all(d >= 1 for d in v0.inclusion_delays.values())
    # the only possible miss is epoch 0 (a slot-0 duty is never attested —
    # the harness starts producing at slot 1); epochs 1+ are all hits
    assert v0.attestations_missed <= 1
    assert {1, 2} <= v0.attested_epochs
    assert mon.summary(5).attestations_included >= 2


def test_block_times_cache_records_pipeline():
    h = _harness()
    h.extend_chain(2)
    root = h.chain.head_root
    times = h.chain.block_times_cache.get(root)
    assert times is not None
    assert times.observed_at is not None
    assert times.imported_at is not None
    assert times.became_head_at is not None
    assert times.imported_at >= times.observed_at
    assert "observed_to_imported" in times.all_delays


def test_state_advance_precompute_used_by_import():
    from lighthouse_tpu.metrics import REGISTRY

    h = _harness()
    h.extend_chain(2)
    timer = StateAdvanceTimer(h.chain)
    cur = h.chain.head_state.slot
    timer.on_slot_tick(cur)  # pre-builds state for slot cur+1
    cached = h.chain.state_advance_cache._state
    assert cached is not None and cached.slot == cur + 1
    # import at cur+1 consumes the pre-advanced state (a hit, not a
    # waste); the head move to the imported block then drops the entry,
    # which was keyed off the now-old head
    hits = REGISTRY.counter("state_advance_hits_total")
    wasted = REGISTRY.counter("state_advance_wasted_total")
    before_h, before_w = hits.value(), wasted.value()
    h.slot_clock.set_slot(cur + 1)
    h.add_block_at_slot(cur + 1)
    assert hits.value() == before_h + 1
    assert wasted.value() == before_w
    assert h.chain.state_advance_cache._state is None  # head moved on
    assert h.chain.head_state.slot == cur + 1


def test_fork_revert_wipes_descendants_and_blacklists():
    h = _harness()
    h.extend_chain(6, attest=False)
    head6 = h.chain.head_root
    blk4 = None
    # find the block at slot 4 (to revert it + slots 5,6)
    for root, signed in h.chain._blocks_by_root.items():
        if signed.message.slot == 4:
            blk4 = root
    assert blk4 is not None
    wiped = revert_to_fork_boundary(h.chain, blk4)
    assert wiped == 3  # slots 4, 5, 6
    assert h.chain.head_root != head6
    assert h.chain.head_state.slot == 3
    assert blk4 in h.chain.invalid_block_roots
    # a re-import of the reverted segment is refused
    from lighthouse_tpu.beacon_chain.chain import BlockError

    sig4 = h.chain.store.get_block(blk4)
    assert sig4 is None  # wiped from the store too
    # the chain continues cleanly from the revert point
    h.slot_clock.set_slot(7)
    h.add_block_at_slot(7)
    assert h.chain.head_state.slot == 7


def test_compare_fields_pinpoints_divergence():
    """compare_fields derive analog: field-wise state diffing."""
    from lighthouse_tpu.utils.compare_fields import compare_fields

    h = _harness()
    a = h.chain.head_state
    b = a.copy()
    assert compare_fields(a, b) == []
    b.slot = 99
    b.balances[3] = 123
    b.validators.mutate(1).slashed = True  # CoW: never b.validators[1].x =
    diffs = {d.path: d for d in compare_fields(a, b)}
    assert any(p.endswith(".slot") for p in diffs)
    assert any("balances[3]" in p for p in diffs)
    assert any("validators[1].slashed" in p for p in diffs)
    assert len(diffs) == 3


def test_registry_elements_are_frozen_against_direct_mutation():
    """milhouse &mut discipline (beacon_state.rs:34): a direct field write
    on a registry element shared across state copies must raise, not
    silently corrupt the sibling copy."""
    import pytest

    from lighthouse_tpu.ssz.core import FrozenElementError

    h = _harness()
    a = h.chain.head_state
    b = a.copy()
    with pytest.raises(FrozenElementError):
        b.validators[1].slashed = True
    # the original is untouched and the sanctioned path still works
    assert a.validators[1].slashed is False
    b.validators.mutate(1).slashed = True
    assert b.validators[1].slashed is True
    assert a.validators[1].slashed is False
    # a clone handed out by mutate() is re-frozen once the list is copied
    v = b.validators.mutate(2)
    v.effective_balance = 7
    c = b.copy()  # noqa: F841 — blocks now shared again
    with pytest.raises(FrozenElementError):
        v.effective_balance = 8


def test_fork_revert_refuses_finalized():
    h = _harness()
    h.extend_chain(4 * E.SLOTS_PER_EPOCH)
    fin = h.chain.finalized_checkpoint
    assert fin.epoch >= 1
    with pytest.raises(RuntimeError, match="weak subjectivity"):
        revert_to_fork_boundary(h.chain, bytes(fin.root))
