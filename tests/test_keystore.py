"""EIP-2333 key derivation + EIP-2335 keystores + AES core.

Known-answer tests: FIPS-197 for AES, the EIP-2333 spec test case, and the
EIP-2335 spec scrypt/pbkdf2 vectors."""

import json

import pytest

from lighthouse_tpu.crypto.aes import _encrypt_block, _expand_key, aes128_ctr
from lighthouse_tpu.crypto.key_derivation import (
    derive_child_sk,
    derive_master_sk,
    derive_sk_from_path,
    validator_keypair_path,
)
from lighthouse_tpu.crypto.keystore import Keystore, KeystoreError


def test_aes_fips197_vector():
    key = bytes(range(16))
    pt = bytes.fromhex("00112233445566778899aabbccddeeff")
    assert (
        _encrypt_block(pt, _expand_key(key)).hex()
        == "69c4e0d86a7b0430d8cdb78070b4c55a"
    )


def test_aes_ctr_roundtrip():
    key = b"\x01" * 16
    iv = b"\x02" * 16
    data = b"hello keystore world, this is longer than one block"
    ct = aes128_ctr(key, iv, data)
    assert ct != data
    assert aes128_ctr(key, iv, ct) == data


def test_eip2333_test_case_0():
    seed = bytes.fromhex(
        "c55257c360c07c72029aebc1b53c05ed0362ada38ead3e3e9efa3708e53495531f"
        "09a6987599d18264c1e1c92f2cf141630c7a3c4ab7c81b2f001698e7463b04"
    )
    master = derive_master_sk(seed)
    assert master == int(
        "6083874454709270928345386274498605044986640685124978867557563392430687146096"
    )
    child = derive_child_sk(master, 0)
    assert child == int(
        "20397789859736650942317412262472558107875392172444076792671091975210932703118"
    )


def test_eip2334_path_derivation():
    seed = b"\x42" * 32
    direct = derive_child_sk(
        derive_child_sk(
            derive_child_sk(
                derive_child_sk(derive_master_sk(seed), 12381), 3600
            ),
            5,
        ),
        0,
    )
    via_path = derive_sk_from_path(seed, "m/12381/3600/5/0")
    assert direct == via_path
    assert validator_keypair_path(5) == "m/12381/3600/5/0/0"
    with pytest.raises(ValueError):
        derive_sk_from_path(seed, "x/12381")


# EIP-2335 spec test vectors (scrypt + pbkdf2): password, secret, and full
# keystore JSON from the EIP.
_EIP2335_PASSWORD = "\U0001D531\U0001D522\U0001D530\U0001D531\U0001D52D\U0001D51E\U0001D530\U0001D530\U0001D534\U0001D52C\U0001D52F\U0001D521\U0001F511"
_EIP2335_SECRET = bytes.fromhex(
    "000000000019d6689c085ae165831e934ff763ae46a2a6c172b3f1b60a8ce26f"
)

_SCRYPT_VECTOR = {
    "crypto": {
        "kdf": {
            "function": "scrypt",
            "params": {
                "dklen": 32,
                "n": 262144,
                "p": 1,
                "r": 8,
                "salt": "d4e56740f876aef8c010b86a40d5f56745a118d0906a34e69aec8c0db1cb8fa3",
            },
            "message": "",
        },
        "checksum": {
            "function": "sha256",
            "params": {},
            "message": "d2217fe5f3e9a1e34581ef8a78f7c9928e436d36dacc5e846690a5581e8ea484",
        },
        "cipher": {
            "function": "aes-128-ctr",
            "params": {"iv": "264daa3f303d7259501c93d997d84fe6"},
            "message": "06ae90d55fe0a6e9c5c3bc5b170827b2e5cce3929ed3f116c2811e6366dfe20f",
        },
    },
    "description": "This is a test keystore that uses scrypt to secure the secret.",
    "pubkey": "9612d7a727c9d0a22e185a1c768478dfe919cada9266988cb32359c11f2b7b27f4ae4040902382ae2910c15e2b420d07",
    "path": "m/12381/60/3141592653/589793238",
    "uuid": "1d85ae20-35c5-4611-98e8-aa14a633906f",
    "version": 4,
}

_PBKDF2_VECTOR = {
    "crypto": {
        "kdf": {
            "function": "pbkdf2",
            "params": {
                "dklen": 32,
                "c": 262144,
                "prf": "hmac-sha256",
                "salt": "d4e56740f876aef8c010b86a40d5f56745a118d0906a34e69aec8c0db1cb8fa3",
            },
            "message": "",
        },
        "checksum": {
            "function": "sha256",
            "params": {},
            "message": "8a9f5d9912ed7e75ea794bc5a89bca5f193721d30868ade6f73043c6ea6febf1",
        },
        "cipher": {
            "function": "aes-128-ctr",
            "params": {"iv": "264daa3f303d7259501c93d997d84fe6"},
            "message": "cee03fde2af33149775b7223e7845e4fb2c8ae1792e5f99fe9ecf474cc8c16ad",
        },
    },
    "description": "This is a test keystore that uses PBKDF2 to secure the secret.",
    "pubkey": "9612d7a727c9d0a22e185a1c768478dfe919cada9266988cb32359c11f2b7b27f4ae4040902382ae2910c15e2b420d07",
    "path": "m/12381/60/0/0",
    "uuid": "64625def-3331-4eea-ab6f-782f3ed16a83",
    "version": 4,
}


@pytest.mark.slow
def test_eip2335_scrypt_vector():
    ks = Keystore.from_json(json.dumps(_SCRYPT_VECTOR))
    assert ks.decrypt(_EIP2335_PASSWORD) == _EIP2335_SECRET
    with pytest.raises(KeystoreError):
        ks.decrypt("wrong password")


@pytest.mark.slow
def test_eip2335_pbkdf2_vector():
    ks = Keystore.from_json(json.dumps(_PBKDF2_VECTOR))
    assert ks.decrypt(_EIP2335_PASSWORD) == _EIP2335_SECRET


def test_keystore_roundtrip(tmp_path):
    from lighthouse_tpu.crypto import bls

    bls.set_backend("host")
    secret = (12345).to_bytes(32, "big")
    ks = Keystore.encrypt(
        secret, "hunter2", path="m/12381/3600/0/0/0", _fast_kdf=True
    )
    p = tmp_path / "ks.json"
    ks.save(p)
    loaded = Keystore.load(p)
    assert loaded.decrypt("hunter2") == secret
    assert loaded.pubkey == bls.SecretKey.from_bytes(secret).public_key().to_bytes()
    with pytest.raises(KeystoreError):
        loaded.decrypt("wrong")

    ks2 = Keystore.encrypt(secret, "pw", kdf="pbkdf2", _fast_kdf=True)
    assert ks2.decrypt("pw") == secret
