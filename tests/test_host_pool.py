"""Host fork-pool lifecycle: sizing, degrade, reuse, and failure surface.

The pool must be boring: identical verification results at any size,
exceptions that surface instead of hanging, workers that survive across
batches, and a task counter the metrics exposition always carries
(tests/conftest.py asserts the eager registration at session start).
"""

import os
import random

import pytest

from lighthouse_tpu.crypto import bls
from lighthouse_tpu.metrics import REGISTRY
from lighthouse_tpu.parallel import host_pool
from lighthouse_tpu.parallel.host_pool import BrokenProcessPool, HostPool


@pytest.fixture(autouse=True)
def fresh_pool():
    bls.set_backend("host")
    host_pool.reset_pool()
    yield
    host_pool.reset_pool()


def _square(x):
    return x * x


def _boom(x):
    raise RuntimeError("worker task exploded")


def _exit_hard(x):
    os._exit(13)  # simulate an OOM-killed worker: no exception, no result


def _sets(n, n_msgs=4):
    kps = bls.interop_keypairs(3)
    out = []
    for i in range(n):
        m = bytes([i % n_msgs]) * 32
        kp = kps[i % 3]
        out.append(bls.SignatureSet(kp.sk.sign(m), [kp.pk], m))
    return out


def test_inline_degrade_at_size_leq_one():
    for size in (0, 1):
        p = HostPool(size)
        assert p.inline
        assert p.map(_square, [1, 2, 3]) == [1, 4, 9]
        assert p._executor is None  # never forked


def test_fork_pool_maps_in_order():
    p = HostPool(4)
    try:
        assert p.map(_square, list(range(17))) == [x * x for x in range(17)]
    finally:
        p.shutdown()


def test_results_identical_across_pool_sizes(monkeypatch):
    sets = _sets(12)
    expected = bls._BACKENDS["host"].verify_signature_sets_serial(
        sets, random.Random(9)
    )
    assert expected is True
    tampered = list(sets)
    tampered[5] = bls.SignatureSet(
        sets[4].signature, sets[5].pubkeys, sets[5].message
    )
    for size in ("0", "1", "4"):
        monkeypatch.setenv(host_pool.ENV_VAR, size)
        host_pool.reset_pool()
        assert bls.verify_signature_sets(sets, random.Random(9)) is True, size
        assert (
            bls.verify_signature_sets(tampered, random.Random(9)) is False
        ), size


def test_env_resize_replaces_pool(monkeypatch):
    monkeypatch.setenv(host_pool.ENV_VAR, "2")
    p2 = host_pool.get_pool()
    assert p2.size == 2 and host_pool.get_pool() is p2  # stable while env is
    monkeypatch.setenv(host_pool.ENV_VAR, "3")
    p3 = host_pool.get_pool()
    assert p3.size == 3 and p3 is not p2


def test_pool_survives_reuse_across_batches(monkeypatch):
    monkeypatch.setenv(host_pool.ENV_VAR, "2")
    sets = _sets(10)
    assert bls.verify_signature_sets(sets, random.Random(1)) is True
    p = host_pool.get_pool()
    ex = p._executor
    assert ex is not None  # really forked
    assert bls.verify_signature_sets(sets, random.Random(2)) is True
    assert host_pool.get_pool() is p and p._executor is ex  # same workers


def test_worker_exception_propagates_from_map(monkeypatch):
    monkeypatch.setenv(host_pool.ENV_VAR, "2")
    with pytest.raises(RuntimeError, match="worker task exploded"):
        host_pool.get_pool().map(_boom, [1, 2, 3])


def test_worker_exception_is_verification_failure_not_a_hang(monkeypatch):
    monkeypatch.setenv(host_pool.ENV_VAR, "2")
    host_pool.reset_pool()
    sets = _sets(8)
    monkeypatch.setattr(bls, "_prep_chunk", _boom)
    assert bls.verify_signature_sets(sets, random.Random(3)) is False


def test_dead_worker_breaks_pool_then_recovers(monkeypatch):
    monkeypatch.setenv(host_pool.ENV_VAR, "2")
    p = host_pool.get_pool()
    with pytest.raises(BrokenProcessPool):
        p.map(_exit_hard, [1, 2, 3])
    assert p._executor is None  # dead executor discarded, not leaked
    # same pool object forks fresh workers and serves the next batch
    assert p.map(_square, [5, 6]) == [25, 36]
    assert bls.verify_signature_sets(_sets(6), random.Random(4)) is True


def test_pool_task_counter_counts_modes(monkeypatch):
    counter = REGISTRY.counter("bls_pool_tasks_total")
    inline0 = counter.value(mode="inline")
    fork0 = counter.value(mode="fork")
    HostPool(1).map(_square, [1, 2])
    assert counter.value(mode="inline") == inline0 + 2
    p = HostPool(2)
    try:
        p.map(_square, [1, 2, 3])
    finally:
        p.shutdown()
    assert counter.value(mode="fork") == fork0 + 3


def test_shard_preserves_order_and_bounds():
    assert host_pool.shard([], 4) == []
    assert host_pool.shard([1, 2, 3], 1) == [[1, 2, 3]]
    chunks = host_pool.shard(list(range(10)), 3)
    assert len(chunks) <= 3  # contiguous ceil-split
    assert [x for c in chunks for x in c] == list(range(10))
    assert host_pool.shard([1], 8) == [[1]]
