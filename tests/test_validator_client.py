"""Validator client services + EIP-3076 slashing protection.

Mirrors validator_client tests: duties lookup, per-slot attest/propose
against an in-process beacon node, slashing refusals, interchange
import/export, doppelganger gating. The VC (not the harness) drives a
chain to finality in the e2e."""

from dataclasses import replace

import pytest

from lighthouse_tpu.beacon_chain.harness import BeaconChainHarness
from lighthouse_tpu.crypto import bls
from lighthouse_tpu.types.chain_spec import minimal_spec
from lighthouse_tpu.types.eth_spec import MinimalEthSpec as E
from lighthouse_tpu.validator_client import ValidatorClient
from lighthouse_tpu.validator_client.slashing_protection import (
    NotSafe,
    SlashingDatabase,
)


# --- slashing protection ----------------------------------------------------


def test_block_proposal_protection():
    db = SlashingDatabase()
    pk = b"\xaa" * 48
    db.register_validator(pk)
    db.check_and_insert_block_proposal(pk, 10, b"\x01" * 32)
    # same slot + same root: idempotent
    db.check_and_insert_block_proposal(pk, 10, b"\x01" * 32)
    # same slot, different root: double proposal
    with pytest.raises(NotSafe):
        db.check_and_insert_block_proposal(pk, 10, b"\x02" * 32)
    # lower slot: refused
    with pytest.raises(NotSafe):
        db.check_and_insert_block_proposal(pk, 9, b"\x03" * 32)
    db.check_and_insert_block_proposal(pk, 11, b"\x04" * 32)


def test_attestation_protection():
    db = SlashingDatabase()
    pk = b"\xbb" * 48
    db.register_validator(pk)
    db.check_and_insert_attestation(pk, 2, 3, b"\x01" * 32)
    db.check_and_insert_attestation(pk, 3, 4, b"\x02" * 32)
    # double vote (same target, different root)
    with pytest.raises(NotSafe):
        db.check_and_insert_attestation(pk, 2, 4, b"\x03" * 32)
    # surround: (1, 5) surrounds (3, 4)
    with pytest.raises(NotSafe):
        db.check_and_insert_attestation(pk, 1, 5, b"\x04" * 32)
    # surrounded: with (2,3) and (3,4) recorded, (3.., ..) inside an
    # existing span — craft (2,3)-surrounding first then test inner
    db.check_and_insert_attestation(pk, 4, 7, b"\x05" * 32)
    with pytest.raises(NotSafe):
        db.check_and_insert_attestation(pk, 5, 6, b"\x06" * 32)
    # unregistered validator
    with pytest.raises(NotSafe):
        db.check_and_insert_attestation(b"\xcc" * 48, 1, 2, b"\x00" * 32)


def test_interchange_roundtrip():
    db = SlashingDatabase()
    pk = b"\xdd" * 48
    db.register_validator(pk)
    db.check_and_insert_block_proposal(pk, 5, b"\x01" * 32)
    db.check_and_insert_attestation(pk, 1, 2, b"\x02" * 32)
    gvr = b"\x99" * 32
    doc = db.export_interchange(gvr)
    assert doc["metadata"]["interchange_format_version"] == "5"

    db2 = SlashingDatabase()
    db2.import_interchange(doc, gvr)
    # imported history still protects
    with pytest.raises(NotSafe):
        db2.check_and_insert_block_proposal(pk, 5, b"\x07" * 32)
    with pytest.raises(NotSafe):
        db2.check_and_insert_attestation(pk, 1, 2, b"\x08" * 32)
    # wrong genesis root refused
    with pytest.raises(NotSafe):
        SlashingDatabase().import_interchange(doc, b"\x00" * 32)


# --- validator client e2e ---------------------------------------------------


def _vc_setup(validator_count=16):
    bls.set_backend("fake_crypto")
    spec = replace(minimal_spec(), altair_fork_epoch=0)
    h = BeaconChainHarness(spec, E, validator_count=validator_count)
    vc = ValidatorClient(h.chain, h.keypairs, spec, E)
    return h, vc


def test_duties_cover_every_managed_validator():
    h, vc = _vc_setup()
    duties = vc.duties_service.attester_duties(0)
    assert sorted(d.validator_index for d in duties) == list(range(16))
    # every slot is a valid epoch-0 slot
    assert all(0 <= d.slot < E.SLOTS_PER_EPOCH for d in duties)


def test_vc_drives_chain_to_finality():
    """The VC proposes and attests for 4 epochs; finality advances — the
    block/attestation path runs through ValidatorStore signing + slashing
    protection instead of the harness's direct signing."""
    h, vc = _vc_setup()
    for slot in range(1, 4 * E.SLOTS_PER_EPOCH + 1):
        h.slot_clock.set_slot(slot)
        root = vc.on_slot(slot)
        assert root is not None, f"no proposal at slot {slot} (all keys managed)"
    assert h.finalized_epoch >= 2
    # slashing DB recorded every proposal + attestation
    db = vc.store.slashing_db
    pk0 = h.keypairs[0].pk.to_bytes()
    with pytest.raises(NotSafe):
        db.check_and_insert_block_proposal(pk0, 1, b"\x00" * 32)


def test_vc_sync_committee_and_preparation_services():
    """Real crypto: the VC's SyncCommitteeService signs head roots into
    the chain's sync-message pool; the next produced block carries a
    non-empty, spec-valid SyncAggregate. PreparationService registers fee
    recipients via prepare_beacon_proposer (sync_committee_service.rs,
    preparation_service.rs)."""
    bls.set_backend("host")
    try:
        spec = replace(minimal_spec(), altair_fork_epoch=0)
        h = BeaconChainHarness(spec, E, validator_count=8)
        vc = ValidatorClient(
            h.chain, h.keypairs, spec, E, fee_recipient=b"\xaa" * 20
        )
        for slot in range(1, 4):
            h.slot_clock.set_slot(slot)
            vc.on_slot(slot)
        # block at slot 2+ was produced from the pool, not the empty
        # aggregate: all committee members are managed, so full bits
        head_block = h.chain.head_block()
        agg = head_block.message.body.sync_aggregate
        assert any(agg.sync_committee_bits), "pool-built aggregate is empty"
        # process_sync_aggregate accepted it during import (signature
        # checked under host crypto) — the head advanced to slot 3
        assert h.chain.head_state.slot == 3
        # preparation reached the chain
        assert h.chain.proposer_preparations
        assert set(h.chain.proposer_preparations.values()) == {b"\xaa" * 20}
    finally:
        bls.set_backend("fake_crypto")


def test_vc_aggregation_duties():
    """Selected aggregators publish SignedAggregateAndProofs built from
    the pool's best aggregate; the chain verifies all three signatures
    (selection proof, aggregator, attestation) under real crypto."""
    bls.set_backend("host")
    try:
        spec = replace(minimal_spec(), altair_fork_epoch=0)
        h = BeaconChainHarness(spec, E, validator_count=8)
        vc = ValidatorClient(h.chain, h.keypairs, spec, E)
        published = []
        for slot in range(1, 5):
            h.slot_clock.set_slot(slot)
            vc.block_service.propose_if_due(slot)
            head = h.chain.head_root
            vc.attestation_service.attest(slot, head)
            published += vc.attestation_service.aggregate_if_selected(slot)
        # minimal-spec TARGET_AGGREGATORS_PER_COMMITTEE makes selection
        # near-certain with these committee sizes; require at least one
        assert published, "no aggregator selected across 4 slots"
        agg = published[0]
        assert sum(agg.message.aggregate.aggregation_bits) >= 1
        # the chain accepted it into the observed-aggregators dedup
        data = agg.message.aggregate.data
        assert h.chain.observed_aggregators.is_known(
            data.target.epoch, agg.message.aggregator_index
        )
    finally:
        bls.set_backend("fake_crypto")


def test_sync_message_rejects_non_member_and_bad_signature():
    from lighthouse_tpu.beacon_chain.sync_pool import SyncMessageError

    bls.set_backend("host")
    try:
        spec = replace(minimal_spec(), altair_fork_epoch=0)
        h = BeaconChainHarness(spec, E, validator_count=8)
        t = h.chain.types
        # bad signature for a real member
        state = h.chain.head_state
        member_pk = bytes(state.current_sync_committee.pubkeys[0])
        vi = next(
            i for i, v in enumerate(state.validators)
            if bytes(v.pubkey) == member_pk
        )
        msg = t.SyncCommitteeMessage(
            slot=0,
            beacon_block_root=h.chain.head_root,
            validator_index=vi,
            signature=b"\x01" * 96,
        )
        with pytest.raises(SyncMessageError, match="signature"):
            h.chain.process_sync_committee_message(msg)
        with pytest.raises(SyncMessageError, match="unknown validator"):
            h.chain.process_sync_committee_message(
                t.SyncCommitteeMessage(
                    slot=0,
                    beacon_block_root=h.chain.head_root,
                    validator_index=10_000,
                    signature=b"\x01" * 96,
                )
            )
    finally:
        bls.set_backend("fake_crypto")


def test_vc_refuses_repeat_slot_proposal():
    h, vc = _vc_setup(validator_count=8)
    h.slot_clock.set_slot(1)
    root = vc.on_slot(1)
    assert root is not None
    # re-running the same slot: block may be rebuilt with a different
    # state (e.g. new attestations) — slashing protection must refuse a
    # conflicting second signature rather than double-sign
    import lighthouse_tpu.validator_client as V

    from lighthouse_tpu.types.containers import build_types

    head = h.chain.head_block()
    pubkey = h.keypairs[head.message.proposer_index].pk.to_bytes()
    t = build_types(E)
    tf = t.types_for_fork(t.fork_of_block(head.message))
    conflicting = tf.BeaconBlock(
        slot=1,
        proposer_index=head.message.proposer_index,
        parent_root=head.message.parent_root,
        state_root=b"\x42" * 32,  # differs from the signed block
        body=tf.BeaconBlockBody(),
    )
    with pytest.raises(NotSafe):
        vc.store.sign_block(pubkey, conflicting, h.chain.head_state, vc.spec, E)


def test_doppelganger_gates_signing():
    h, vc = _vc_setup(validator_count=8)
    vc.doppelganger.begin(current_epoch=0)
    h.slot_clock.set_slot(1)
    assert vc.on_slot(1) is None  # gated
    later_slot = 2 * E.SLOTS_PER_EPOCH + 1
    h.slot_clock.set_slot(later_slot)
    assert vc.doppelganger.signing_enabled(2)


def test_keymanager_api_lifecycle():
    """VC keymanager HTTP API (validator_client/src/http_api): bearer
    auth, list/import/delete keystores with interchange export, fee
    recipient get/set feeding the preparation service."""
    import json as _json
    import urllib.request
    from urllib.error import HTTPError

    from lighthouse_tpu.crypto.keystore import Keystore
    from lighthouse_tpu.validator_client.http_api import KeymanagerServer

    h, vc = _vc_setup(validator_count=4)
    srv = KeymanagerServer(vc).start()
    base = f"http://127.0.0.1:{srv.port}"

    def call(method, path, body=None, token=srv.token):
        req = urllib.request.Request(
            f"{base}{path}",
            data=_json.dumps(body).encode() if body is not None else None,
            method=method,
            headers={
                "Authorization": f"Bearer {token}",
                "Content-Type": "application/json",
            },
        )
        with urllib.request.urlopen(req, timeout=5) as r:
            return r.status, _json.loads(r.read())

    try:
        # auth required
        try:
            call("GET", "/eth/v1/keystores", token="wrong")
            raise AssertionError("unauthenticated request accepted")
        except HTTPError as e:
            assert e.code == 401

        _code, listed = call("GET", "/eth/v1/keystores")
        assert len(listed["data"]) == 4

        # import a 5th key
        kp5 = bls.interop_keypairs(6)[5]
        ks = Keystore.encrypt(
            kp5.sk.scalar.to_bytes(32, "big"), "pw",
            pubkey=kp5.pk.to_bytes(), _fast_kdf=True,
        )
        _code, res = call(
            "POST", "/eth/v1/keystores",
            {"keystores": [ks.to_json()], "passwords": ["pw"]},
        )
        assert res["data"] == [{"status": "imported"}]
        assert len(call("GET", "/eth/v1/keystores")[1]["data"]) == 5
        # duplicate import reports duplicate
        _code, res = call(
            "POST", "/eth/v1/keystores",
            {"keystores": [ks.to_json()], "passwords": ["pw"]},
        )
        assert res["data"] == [{"status": "duplicate"}]

        # fee recipient set/get drives the preparation service
        pk_hex = "0x" + kp5.pk.to_bytes().hex()
        code, _ = call(
            "POST", f"/eth/v1/validator/{pk_hex}/feerecipient",
            {"ethaddress": "0x" + "ee" * 20},
        )
        assert code == 202
        _code, fr = call("GET", f"/eth/v1/validator/{pk_hex}/feerecipient")
        assert fr["data"]["ethaddress"] == "0x" + "ee" * 20

        # delete exports slashing protection
        _code, res = call("DELETE", "/eth/v1/keystores", {"pubkeys": [pk_hex]})
        assert res["data"] == [{"status": "deleted"}]
        interchange = _json.loads(res["slashing_protection"])
        assert "metadata" in interchange
        assert len(call("GET", "/eth/v1/keystores")[1]["data"]) == 4
    finally:
        srv.stop()
