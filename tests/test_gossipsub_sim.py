"""Gossipsub mesh in the live multi-node network (acceptance scenario).

Scenario 1: a misbehaving peer floods garbage blocks; its gossipsub score
crosses the graylist threshold on every honest node, the next heartbeat
PRUNEs it from their meshes (with backoff recorded on both sides), its
subsequent frames are dropped before validation — and honest block gossip
keeps flowing between the remaining nodes.

Scenario 2: lazy-pull recovery — a node that missed a block's eager push
entirely (it wasn't connected when the block was published, and its
scores keep it out of the publisher's mesh) recovers the block purely via
heartbeat IHAVE → IWANT → PUBLISH.
"""

import time
from dataclasses import replace

import pytest

from lighthouse_tpu.beacon_chain.harness import BeaconChainHarness
from lighthouse_tpu.crypto import bls
from lighthouse_tpu.metrics import REGISTRY
from lighthouse_tpu.network import BAN_THRESHOLD, NetworkService
from lighthouse_tpu.network.gossipsub import PeerScoreThresholds
from lighthouse_tpu.types.chain_spec import minimal_spec
from lighthouse_tpu.types.eth_spec import MinimalEthSpec as E


def _harness(slots=0):
    bls.set_backend("fake_crypto")
    spec = replace(minimal_spec(), altair_fork_epoch=0)
    h = BeaconChainHarness(spec, E, validator_count=16)
    if slots:
        h.extend_chain(slots)
    return h


def _wait(predicate, timeout=5.0, what="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {what}")


#: strict thresholds for the scenario: 3 invalid blocks (-2·3² = -18 on
#: the block topic) cross ALL of them, while the PeerManager's ban
#: (4 × -10 vs -40) does NOT fire — isolating the gossipsub response
STRICT = PeerScoreThresholds(
    gossip_threshold=-10.0,
    publish_threshold=-12.0,
    graylist_threshold=-15.0,
    accept_px_threshold=10.0,
    opportunistic_graft_threshold=1.0,
)


def test_misbehaving_peer_graylisted_pruned_and_ignored():
    a = _harness(slots=E.SLOTS_PER_EPOCH)
    b = _harness()
    m = _harness()
    na = NetworkService(a.chain, heartbeat_interval=0, gossip_thresholds=STRICT)
    nb = NetworkService(b.chain, heartbeat_interval=0, gossip_thresholds=STRICT)
    nm = NetworkService(m.chain, heartbeat_interval=0)
    for n in (na, nb, nm):
        n.start()
    try:
        b.slot_clock.set_slot(a.chain.head_state.slot)
        m.slot_clock.set_slot(a.chain.head_state.slot)
        peer_ab = nb.connect("127.0.0.1", na.port)
        nb.sync.sync_with(peer_ab)
        peer_mb = nm.connect("127.0.0.1", na.port)
        nm.sync.sync_with(peer_mb)
        nm.connect("127.0.0.1", nb.port)
        m_id_at_a = f"127.0.0.1:{nm.port}"
        m_id_at_b = f"127.0.0.1:{nm.port}"
        b_id_at_a = f"127.0.0.1:{nb.port}"
        topic = na.topic_block

        # subscriptions must have propagated BOTH ways before meshes can
        # form (and before M's floods have any targets)
        a_id_at_m = f"127.0.0.1:{na.port}"
        b_id_at_m = f"127.0.0.1:{nb.port}"
        for svc, pid in (
            (na, m_id_at_a),
            (na, b_id_at_a),
            (nb, m_id_at_b),
            (nm, a_id_at_m),
            (nm, b_id_at_m),
        ):
            _wait(
                lambda s=svc, p=pid: topic
                in s.gossip.behaviour.peer_topics.get(p, ()),
                what=f"subscription of {pid}",
            )
        for n in (na, nb, nm):
            n.gossip.heartbeat()
        assert m_id_at_a in na.gossip.mesh_peers(topic)
        assert m_id_at_b in nb.gossip.mesh_peers(topic)

        # -- misbehave: 3 undecodable blocks flood-published by M --------
        for i in range(3):
            nm.gossip.publish(nm.topic_block, b"garbage-block-%d" % i)
        for svc, pid in ((na, m_id_at_a), (nb, m_id_at_b)):
            _wait(
                lambda s=svc, p=pid: s.gossip.behaviour.peer_score(p)
                < STRICT.graylist_threshold,
                what=f"graylist crossing at {pid}",
            )
        # the PeerManager saw 3 invalid reports (-30): demoted, NOT banned
        # — the mesh response below is gossipsub's own
        mgr_peer = na.peers.get(m_id_at_a)
        assert mgr_peer is not None and not mgr_peer.banned
        assert BAN_THRESHOLD < mgr_peer.score <= -30.0

        # -- heartbeat: negative-score member is PRUNEd with backoff -----
        na.gossip.heartbeat()
        nb.gossip.heartbeat()
        assert m_id_at_a not in na.gossip.mesh_peers(topic)
        assert m_id_at_b not in nb.gossip.mesh_peers(topic)
        assert na.gossip.behaviour.backoff.get((topic, m_id_at_a), 0) > 0
        # M received the PRUNEs and recorded its own backoff against both
        _wait(
            lambda: (topic, a_id_at_m) in nm.gossip.behaviour.backoff
            and (topic, b_id_at_m) in nm.gossip.behaviour.backoff,
            what="PRUNE backoff recorded on the misbehaving node",
        )

        # -- graylisted: further frames dropped before validation --------
        dropped = REGISTRY.counter("gossipsub_graylist_dropped_total")
        before_drops = dropped.value()
        before_mgr_score = mgr_peer.score
        nm.gossip.publish(nm.topic_block, b"garbage-block-99")
        _wait(
            lambda: dropped.value() >= before_drops + 2,  # dropped at A and B
            what="graylist drops counted",
        )
        assert mgr_peer.score == before_mgr_score  # handler never ran

        # -- honest gossip still flows ----------------------------------
        slot = a.chain.head_state.slot + 1
        for h in (a, b, m):
            h.slot_clock.set_slot(slot)
        root, signed = a.add_block_at_slot(slot)
        na.publish_block(signed)
        _wait(lambda: b.chain.head_root == root, what="honest propagation")
        # the graylisted peer was excluded from the flood and both meshes
        assert m.chain.head_root != root
    finally:
        for n in (na, nb, nm):
            n.stop()


def test_late_joiner_recovers_block_via_ihave_iwant():
    a = _harness(slots=4)
    c = _harness()
    na = NetworkService(a.chain, heartbeat_interval=0)
    nc = NetworkService(c.chain, heartbeat_interval=0)
    na.start()
    nc.start()
    try:
        # replicate A's chain into C out-of-band (RPC, not gossip)
        c.slot_clock.set_slot(a.chain.head_state.slot)
        blocks = na.blocks_by_range(1, a.chain.head_state.slot)
        result = c.chain.process_chain_segment(blocks)
        assert result.error is None and c.chain.head_root == a.chain.head_root

        # A produces and publishes a block while C is NOT connected: the
        # eager push misses C entirely; only A's mcache remembers it
        slot = a.chain.head_state.slot + 1
        a.slot_clock.set_slot(slot)
        c.slot_clock.set_slot(slot)
        root, signed = a.add_block_at_slot(slot)
        na.publish_block(signed)
        assert c.chain.head_root != root

        nc.connect("127.0.0.1", na.port)
        c_id = f"127.0.0.1:{nc.port}"
        topic = na.topic_block
        _wait(
            lambda: topic in na.gossip.behaviour.peer_topics.get(c_id, ()),
            what="late joiner's subscription",
        )
        # keep C out of A's mesh (score < 0) but above the gossip
        # threshold (-40): mesh-ineligible peers are exactly who lazy
        # gossip exists for
        na.gossip.behaviour.score.behaviour_penalty(c_id)
        assert -40 < na.gossip.behaviour.peer_score(c_id) < 0

        served = REGISTRY.counter("gossipsub_iwant_served_total")
        before = served.value()
        na.gossip.heartbeat()  # emits IHAVE to C; C pulls via IWANT
        _wait(lambda: c.chain.head_root == root, what="IHAVE/IWANT recovery")
        assert c_id not in na.gossip.mesh_peers(topic)  # never eager-pushed
        assert served.value() >= before + 1
    finally:
        na.stop()
        nc.stop()


def test_px_records_dialed_after_prune():
    """v1.1 peer exchange: a node pruned from an over-sized mesh learns
    replacement peers from the PRUNE and dials one."""
    a = _harness(slots=2)
    b = _harness()
    c = _harness()
    na = NetworkService(a.chain, heartbeat_interval=0)
    nb = NetworkService(b.chain, heartbeat_interval=0)
    nc = NetworkService(c.chain, heartbeat_interval=0)
    for n in (na, nb, nc):
        n.start()
    try:
        # B and C both peer with A only
        for svc in (nb, nc):
            svc.connect("127.0.0.1", na.port)
        topic = na.topic_block
        b_id, c_id = f"127.0.0.1:{nb.port}", f"127.0.0.1:{nc.port}"
        for pid in (b_id, c_id):
            _wait(
                lambda p=pid: topic in na.gossip.behaviour.peer_topics.get(p, ()),
                what="subscriptions at A",
            )
        na.gossip.heartbeat()
        assert {b_id, c_id} <= na.gossip.mesh_peers(topic)
        # squeeze A's mesh so C gets pruned WITH peer exchange; raise B's
        # score so it is retained and appears in the PX records
        for _ in range(20):
            na.gossip.behaviour.score.first_delivery(b_id, topic)
        # C only accepts PX from peers above accept_px_threshold (10):
        # make A a proven message source from C's point of view
        a_id_at_c = f"127.0.0.1:{na.port}"
        for _ in range(20):
            nc.gossip.behaviour.score.first_delivery(a_id_at_c, topic)
        cfg = na.gossip.behaviour.config
        cfg.d, cfg.d_lo, cfg.d_hi, cfg.d_score = 1, 0, 1, 1
        na.gossip.heartbeat()
        assert na.gossip.mesh_peers(topic) == {b_id}
        # C received PRUNE(px=[B]) and dials B on its next heartbeat
        _wait(
            lambda: (topic, f"127.0.0.1:{na.port}") in nc.gossip.behaviour.backoff,
            what="PRUNE landing at C",
        )
        nc.gossip.heartbeat()
        _wait(
            lambda: any(p.port == nb.port for p in nc.peers.peers()),
            what="PX dial from C to B",
        )
    finally:
        for n in (na, nb, nc):
            n.stop()
