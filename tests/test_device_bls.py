"""Device BLS limb arithmetic vs host bigint oracle.

Field ops run in the default suite (fast compiles); batch point ops and the
full device batch-verify are marked slow (minutes of XLA-CPU compile on
first run; the repo-local persistent cache amortizes them)."""

import random

import jax.numpy as jnp
import numpy as np
import pytest

from lighthouse_tpu.crypto.bls12_381 import (
    FQ,
    FQ2,
    G1_GEN,
    G2_GEN,
    pt_add,
    pt_eq,
    pt_mul,
)
from lighthouse_tpu.crypto.bls12_381.fields import P
from lighthouse_tpu.ops import bls381 as D

# every test in this file is tier-2: device kernels: XLA-CPU compiles take minutes cold.
# tests/conftest.py enforces this marker at collection time.
pytestmark = pytest.mark.slow


def test_limb_roundtrip():
    rng = random.Random(0)
    xs = [0, 1, P - 1] + [rng.randrange(P) for _ in range(5)]
    arr = D.fq_to_device(xs)
    assert D.fq_from_device(arr) == xs


def test_field_ops_vs_bigint():
    rng = random.Random(1)
    xs = [rng.randrange(P) for _ in range(16)]
    ys = [rng.randrange(P) for _ in range(16)]
    ax, ay = jnp.asarray(D.fq_to_device(xs)), jnp.asarray(D.fq_to_device(ys))
    assert D.fq_from_device(D.mont_mul(ax, ay)) == [
        (x * y) % P for x, y in zip(xs, ys)
    ]
    assert D.fq_from_device(D.mod_add(ax, ay)) == [
        (x + y) % P for x, y in zip(xs, ys)
    ]
    assert D.fq_from_device(D.mod_sub(ax, ay)) == [
        (x - y) % P for x, y in zip(xs, ys)
    ]


def test_field_edge_cases():
    edge = [0, P - 1, 1, P - 1, 12345, 0x123456789ABCDEF]
    e = jnp.asarray(D.fq_to_device(edge))
    assert D.fq_from_device(D.mod_sub(e, e)) == [0] * 6
    assert D.fq_from_device(D.mod_add(e, e)) == [(v * 2) % P for v in edge]
    assert D.fq_from_device(D.mont_mul(e, e)) == [(v * v) % P for v in edge]


def test_carry_cascade_regression():
    """Values engineered to produce long 255-chains (the lookahead resolve
    path); ripple passes alone would mis-normalize these."""
    vals = [((1 << 380) - 1) % P, P - 1, ((255 << 376) + 255) % P]
    a = jnp.asarray(D.fq_to_device(vals))
    one = jnp.asarray(D.fq_to_device([1, 1, 1]))
    got = D.fq_from_device(D.mont_mul(a, one))
    assert got == vals


@pytest.mark.slow
def test_g1_batch_scalar_mul():
    rng = random.Random(2)
    pts = [pt_mul(FQ, G1_GEN, rng.randrange(1, 10**9)) for _ in range(8)]
    scalars = [rng.getrandbits(64) for _ in range(8)]
    xs, ys, zs = D.g1_points_to_device(pts)
    bits = jnp.asarray(D.scalars_to_bits(scalars, 64))
    got = D.g1_points_from_device(D.batch_g1_scalar_mul(xs, ys, zs, bits))
    for g, p, s in zip(got, pts, scalars):
        assert pt_eq(FQ, g, pt_mul(FQ, p, s))


@pytest.mark.slow
def test_g1_sum_reduce():
    rng = random.Random(3)
    pts = [pt_mul(FQ, G1_GEN, rng.randrange(1, 10**9)) for _ in range(8)]
    xs, ys, zs = D.g1_points_to_device(pts)
    got = D.g1_points_from_device(D.g1_sum_reduce(xs, ys, zs))[0]
    want = pts[0]
    for p in pts[1:]:
        want = pt_add(FQ, want, p)
    assert pt_eq(FQ, got, want)


@pytest.mark.slow
def test_g2_batch_scalar_mul():
    rng = random.Random(4)
    pts = [pt_mul(FQ2, G2_GEN, rng.randrange(1, 10**9)) for _ in range(8)]
    scalars = [rng.getrandbits(64) for _ in range(8)]
    xs, ys, zs = D.g2_points_to_device(pts)
    bits = jnp.asarray(D.scalars_to_bits(scalars, 64))
    got = D.g2_points_from_device(D.batch_g2_scalar_mul(xs, ys, zs, bits))
    for g, p, s in zip(got, pts, scalars):
        assert pt_eq(FQ2, g, pt_mul(FQ2, p, s))


@pytest.mark.slow
def test_device_verify_signature_sets():
    import hashlib

    from lighthouse_tpu.crypto import bls

    bls.set_backend("host")
    kps = bls.interop_keypairs(8)
    msg = hashlib.sha256(b"device batch").digest()
    sets = [bls.SignatureSet.single(kp.sk.sign(msg), kp.pk, msg) for kp in kps]
    assert D.verify_signature_sets_device(sets, random.Random(5))
    bad = list(sets)
    bad[3] = bls.SignatureSet.single(
        sets[4].signature, sets[3].pubkeys[0], sets[3].message
    )
    assert not D.verify_signature_sets_device(bad, random.Random(6))
