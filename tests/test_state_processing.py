"""State-transition tests (phase0): shuffle, genesis, blocks, epochs, finality.

Spec-logic tests run on the fake_crypto backend (the reference's fake_crypto
double-run, Makefile:148-153); one end-to-end test runs real BLS through the
VERIFY_BULK batch path.
"""

import pytest

from lighthouse_tpu.crypto import bls
from lighthouse_tpu.state_processing import (
    BlockProcessingError,
    BlockSignatureStrategy,
    DepositTree,
    get_beacon_committee,
    get_beacon_proposer_index,
    interop_genesis_state,
    per_slot_processing,
)
from lighthouse_tpu.state_processing.per_block import is_valid_merkle_branch
from lighthouse_tpu.state_processing.shuffle import (
    compute_shuffled_index,
    shuffle_list,
)
from lighthouse_tpu.testing.harness import StateHarness
from lighthouse_tpu.types import MinimalEthSpec, minimal_spec


@pytest.fixture
def fake_crypto():
    bls.set_backend("fake_crypto")
    yield
    bls.set_backend("host")


@pytest.fixture
def harness(fake_crypto):
    return StateHarness(minimal_spec(), MinimalEthSpec, validator_count=64)


def test_shuffle_list_matches_per_index():
    seed = b"\x37" * 32
    for n in (1, 2, 7, 64, 333):
        vals = list(range(n))
        out = shuffle_list(vals, seed, 10)
        assert sorted(out) == vals  # a permutation
        for i in range(n):
            assert out[i] == vals[compute_shuffled_index(i, n, seed, 10)]


def test_shuffle_changes_with_seed():
    vals = list(range(64))
    assert shuffle_list(vals, b"\x01" * 32, 10) != shuffle_list(vals, b"\x02" * 32, 10)


def test_deposit_tree_proofs():
    tree = DepositTree()
    leaves = [bytes([i]) * 32 for i in range(7)]
    for leaf in leaves:
        tree.push(leaf)
    root = tree.root()
    for i, leaf in enumerate(leaves):
        proof = tree.proof(i)
        assert len(proof) == 33
        assert is_valid_merkle_branch(leaf, proof, 33, i, root)
        assert not is_valid_merkle_branch(b"\xff" * 32, proof, 33, i, root)


def test_interop_genesis(harness):
    state = harness.state
    assert len(state.validators) == 64
    assert all(v.activation_epoch == 0 for v in state.validators)
    assert all(b == MinimalEthSpec.MAX_EFFECTIVE_BALANCE for b in state.balances)
    assert state.genesis_validators_root != b"\x00" * 32
    # deterministic
    h2 = StateHarness(minimal_spec(), MinimalEthSpec, validator_count=64)
    assert h2.state.hash_tree_root() == state.hash_tree_root()


def test_committees_cover_all_validators(harness):
    state = harness.state
    E = MinimalEthSpec
    seen = set()
    from lighthouse_tpu.state_processing import committee_cache_at

    cc = committee_cache_at(state, 0, E)
    for slot in range(E.SLOTS_PER_EPOCH):
        for index in range(cc.committees_per_slot):
            seen.update(get_beacon_committee(state, slot, index, E))
    assert seen == set(range(64))


def test_proposer_index_stable(harness):
    state = harness.state.copy()
    p1 = get_beacon_proposer_index(state, MinimalEthSpec)
    p2 = get_beacon_proposer_index(state, MinimalEthSpec)
    assert p1 == p2
    assert 0 <= p1 < 64


def test_empty_slot_advance(harness):
    state = harness.state
    root0 = state.hash_tree_root()
    per_slot_processing(state, harness.spec, MinimalEthSpec)
    assert state.slot == 1
    assert state.hash_tree_root() != root0
    assert state.state_roots[0] == root0


def test_block_import_and_finality(harness):
    harness.extend_chain(8 * 4)
    assert harness.state.slot == 32
    assert harness.justified_epoch == 3
    assert harness.finalized_epoch == 2
    # keep going one epoch: finality advances in lockstep
    harness.extend_chain(8)
    assert harness.justified_epoch == 4
    assert harness.finalized_epoch == 3


def test_no_attestations_no_finality(harness):
    harness.extend_chain(8 * 4, attest=False)
    assert harness.justified_epoch == 0
    assert harness.finalized_epoch == 0


def test_wrong_proposer_rejected(harness):
    produced = harness.produce_block(1, [])
    block = produced.block.message
    bad_proposer = (block.proposer_index + 1) % 64
    t = harness._types()
    bad_block = t.BeaconBlock(
        slot=block.slot,
        proposer_index=bad_proposer,
        parent_root=block.parent_root,
        state_root=block.state_root,
        body=block.body,
    )
    signed = harness.sign_block(bad_block, bad_proposer)
    with pytest.raises(BlockProcessingError, match="proposer"):
        harness.process_block(signed)


def test_state_root_mismatch_rejected(harness):
    produced = harness.produce_block(1, [])
    block = produced.block.message
    t = harness._types()
    bad_block = t.BeaconBlock(
        slot=block.slot,
        proposer_index=block.proposer_index,
        parent_root=block.parent_root,
        state_root=b"\x13" * 32,
        body=block.body,
    )
    signed = harness.sign_block(bad_block, block.proposer_index)
    with pytest.raises(BlockProcessingError, match="state root"):
        harness.process_block(signed)


def test_randao_mix_updates(harness):
    state_before = harness.state.copy()
    harness.extend_chain(1)
    E = MinimalEthSpec
    assert (
        harness.state.randao_mixes[0] != state_before.randao_mixes[0]
    )


def test_eth1_data_votes_accumulate(harness):
    harness.extend_chain(3)
    assert len(harness.state.eth1_data_votes) == 3


@pytest.mark.slow
def test_real_crypto_end_to_end():
    """The SURVEY §7 minimum slice: real BLS through VERIFY_BULK, two epochs,
    spec behavior identical to the fake_crypto path."""
    bls.set_backend("host")
    try:
        h = StateHarness(minimal_spec(), MinimalEthSpec, validator_count=16)
        h.extend_chain(8 * 2)
        assert h.state.slot == 16
        assert len(h.state.previous_epoch_attestations) > 0
        # individual-verification strategy agrees with bulk
        produced = h.produce_block(17, h.produce_attestations(
            h.state.copy(), h.state.slot, h.head_block_root()))
        h.process_block(
            produced.block, strategy=BlockSignatureStrategy.VERIFY_INDIVIDUAL
        )
        assert h.state.slot == 17
    finally:
        bls.set_backend("host")


def test_bad_signature_rejected_real_crypto():
    bls.set_backend("host")
    h = StateHarness(minimal_spec(), MinimalEthSpec, validator_count=16)
    produced = h.produce_block(1, [])
    # tamper: sign with the wrong key
    block = produced.block.message
    t = h._types()
    signed = t.SignedBeaconBlock(
        message=block,
        signature=h.keypairs[(block.proposer_index + 1) % 16]
        .sk.sign(b"\x00" * 32)
        .to_bytes(),
    )
    with pytest.raises(BlockProcessingError, match="signature"):
        h.process_block(signed)
