"""Merkle proofs: chunk branches, container fields, and the Deneb blob
inclusion proof flowing through full BlobSidecar containers + DA checker.

Uses a small (n=64) insecure KZG setup for the blob math and container
shapes with minimal-preset proof depth (9)."""

import random
from dataclasses import replace

import pytest

from lighthouse_tpu.crypto import bls
from lighthouse_tpu.crypto.kzg import FR_MODULUS, Kzg, TrustedSetup
from lighthouse_tpu.ssz.merkle import merkleize, mix_in_length
from lighthouse_tpu.ssz.merkle_proof import (
    build_blob_sidecars,
    compute_blob_inclusion_proof,
    compute_merkle_proof,
    container_field_proof,
    verify_blob_inclusion_proof,
    verify_merkle_proof,
)
from lighthouse_tpu.types.containers import build_types
from lighthouse_tpu.types.eth_spec import MinimalEthSpec as E

T = build_types(E)


def test_chunk_proof_roundtrip():
    rng = random.Random(1)
    chunks = [bytes(rng.randbytes(32)) for _ in range(11)]
    limit = 16
    root = merkleize(chunks, limit=limit)
    for idx in (0, 3, 10):
        branch = compute_merkle_proof(chunks, idx, limit=limit)
        assert verify_merkle_proof(chunks[idx], branch, 4, idx, root)
        assert not verify_merkle_proof(chunks[idx], branch, 4, idx ^ 1, root)
        bad = list(branch)
        bad[1] = b"\x00" * 32
        assert not verify_merkle_proof(chunks[idx], bad, 4, idx, root)


def test_container_field_proof():
    cp = T.Checkpoint(epoch=7, root=b"\x42" * 32)
    att = T.AttestationData(
        slot=9, index=1, beacon_block_root=b"\x11" * 32, source=cp, target=cp
    )
    leaf, branch, idx = container_field_proof(att, "beacon_block_root")
    depth = 3  # 5 fields -> 8 chunks
    assert verify_merkle_proof(leaf, branch, depth, idx, att.hash_tree_root())


@pytest.fixture(scope="module")
def kzg():
    # container-size blobs need the full 4096-point setup (generated once,
    # disk-cached)
    return Kzg(TrustedSetup.insecure_dev())


def _blob(seed, n=E.FIELD_ELEMENTS_PER_BLOB):
    rng = random.Random(seed)
    return b"".join(rng.randrange(FR_MODULUS).to_bytes(32, "big") for _ in range(n))


def test_blob_sidecar_inclusion_proof_roundtrip(kzg):
    bls.set_backend("fake_crypto")
    blobs = [_blob(1), _blob(2)]
    commitments = [kzg.blob_to_kzg_commitment(b) for b in blobs]
    body = T.BeaconBlockBodyDeneb(blob_kzg_commitments=commitments)
    block = T.BeaconBlockDeneb(slot=5, proposer_index=0, body=body)
    signed = T.SignedBeaconBlockDeneb(message=block, signature=b"\x00" * 96)

    sidecars = build_blob_sidecars(signed, blobs, kzg, E)
    assert len(sidecars) == 2
    for sc in sidecars:
        assert len(sc.kzg_commitment_inclusion_proof) == (
            E.KZG_COMMITMENT_INCLUSION_PROOF_DEPTH
        )
        assert verify_blob_inclusion_proof(sc, E)

    # header/body mismatch fails
    bad = sidecars[0].copy()
    hdr = bad.signed_block_header.message.copy()
    hdr.body_root = b"\x99" * 32
    bad.signed_block_header = T.SignedBeaconBlockHeader(
        message=hdr, signature=b"\x00" * 96
    )
    assert not verify_blob_inclusion_proof(bad, E)

    # wrong commitment fails
    bad2 = sidecars[0].copy()
    bad2.kzg_commitment = commitments[1]
    assert not verify_blob_inclusion_proof(bad2, E)


def test_da_checker_enforces_inclusion_proof(kzg):
    from lighthouse_tpu.beacon_chain.data_availability import (
        AvailabilityCheckError,
        DataAvailabilityChecker,
    )

    bls.set_backend("fake_crypto")
    blobs = [_blob(7)]
    commitments = [kzg.blob_to_kzg_commitment(b) for b in blobs]
    body = T.BeaconBlockBodyDeneb(blob_kzg_commitments=commitments)
    block = T.BeaconBlockDeneb(slot=6, proposer_index=1, body=body)
    signed = T.SignedBeaconBlockDeneb(message=block, signature=b"\x00" * 96)
    sidecars = build_blob_sidecars(signed, blobs, kzg, E)

    checker = DataAvailabilityChecker(kzg, E)
    block_root = block.hash_tree_root()
    checker.put_block(block_root, signed)
    avail = checker.put_blobs(block_root, sidecars)
    assert avail.available

    # tampered inclusion proof is rejected outright
    bad = sidecars[0].copy()
    proof = list(bad.kzg_commitment_inclusion_proof)
    proof[-1] = bytes(32)  # body-field sibling: nonzero in a real proof
    assert proof != list(sidecars[0].kzg_commitment_inclusion_proof)
    bad.kzg_commitment_inclusion_proof = proof
    checker2 = DataAvailabilityChecker(kzg, E)
    checker2.put_block(block_root, signed)
    with pytest.raises(AvailabilityCheckError):
        checker2.put_blobs(block_root, [bad])
