"""Benchmark: device Merkleization throughput vs host SHA-256 baseline.

North-star metric 2 (BASELINE.md): tree-hash of a 1M-validator-scale leaf
array. The device path hashes whole tree levels as batched SHA-256
compressions (ops/sha256); the baseline is the host hashlib loop the
reference's ethereum_hashing-backed cache would run per level.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

import hashlib
import json
import sys
import time

import numpy as np

N_LEAVES = 1 << 20  # ~1M leaves: the validators-list scale


def host_merkle_root(data: bytes) -> bytes:
    nodes = [data[i : i + 32] for i in range(0, len(data), 32)]
    while len(nodes) > 1:
        nodes = [
            hashlib.sha256(nodes[i] + nodes[i + 1]).digest()
            for i in range(0, len(nodes), 2)
        ]
    return nodes[0]


def main():
    import jax

    from lighthouse_tpu.ops.sha256 import (
        bytes_to_words,
        merkle_tree_levels,
        words_to_bytes,
    )

    rng = np.random.default_rng(7)
    data = rng.integers(0, 256, size=N_LEAVES * 32, dtype=np.uint8).tobytes()
    leaves = bytes_to_words(data)

    # Device: warm up (compile), then measure.
    dev_leaves = jax.device_put(leaves)
    root_words = merkle_tree_levels(dev_leaves)[0]
    jax.block_until_ready(root_words[0])
    t0 = time.perf_counter()
    runs = 3
    for _ in range(runs):
        root_words = merkle_tree_levels(dev_leaves)[0]
        jax.block_until_ready(root_words[0])
    device_s = (time.perf_counter() - t0) / runs
    device_root = words_to_bytes(root_words)[:32]

    # Host baseline on a slice, extrapolated (full 1M-leaf host run is ~2M
    # hashes; measure 1/16 of the tree and scale).
    slice_leaves = N_LEAVES // 16
    slice_data = data[: slice_leaves * 32]
    t0 = time.perf_counter()
    host_merkle_root(slice_data)
    host_s = (time.perf_counter() - t0) * 16

    # Correctness spot-check on the slice
    slice_root_dev = words_to_bytes(
        merkle_tree_levels(jax.device_put(bytes_to_words(slice_data)))[0]
    )[:32]
    assert slice_root_dev == host_merkle_root(slice_data), "root mismatch!"

    leaves_per_s = N_LEAVES / device_s
    print(
        json.dumps(
            {
                "metric": "merkle_tree_hash_1M_leaves",
                "value": round(leaves_per_s, 1),
                "unit": "leaves/sec",
                "vs_baseline": round(host_s / device_s, 3),
            }
        )
    )


if __name__ == "__main__":
    sys.exit(main())
