"""North-star benchmarks (BASELINE.md) on the live JAX backend.

Headline metric (the one JSON line): **bls_batch_verify_1k** — metric 1,
RLC batch verification of 1024 signature sets (64-pubkey committees, the
reference's gossip batch unit, beacon_processor/src/lib.rs:200). The
default lane is the HOST fast path (Pippenger MSM + fork-pool parallel
pairings, crypto/bls/_HostBackend) — the device lane's XLA compile has
blown every bench cap on this image in five rounds, so the lane that can
actually run is the headline; `BENCH_BLS_LANE=device` opts the device
verifier (ops/bls381_verify) back in, now with a compile-vs-execute time
split. Control for `vs_baseline` is the retained serial per-set RLC loop
(`verify_signature_sets_serial`) on a subsample, same run — blst is not
installable in this image, so the control is an honest same-machine CPU
implementation, NOT a blst number; see BENCH_NOTES.md.

Also measured (emitted in the same JSON line under "details", each with
median-of-N trials and min/max spread):
  * merkle_tree_hash_1M_leaves — metric 2 proxy: device level-batched
    SHA-256 Merkleization of a 1M-leaf array vs host hashlib.
  * block_import_ms — metric 5 at harness scale: full import pipeline
    (signature batch + state transition + fork choice) per block.

Prints a combined JSON line {"metric", "value", "unit", "vs_baseline",
"details"} after every completed metric; the LAST line on stdout is the
authoritative result (the driver reads the tail), so a timeout mid-run
leaves the best finished result instead of nothing.
"""

import hashlib
import json
import os
import random
import statistics
import sys
import time

import numpy as np

# BENCH_SMOKE=1 shrinks every metric to test-scale shapes (kernels already
# compiled by the test suite's persistent cache) — a fast wiring check on
# slow hosts; real numbers come from the full-size run.
SMOKE = os.environ.get("BENCH_SMOKE") == "1"

# Persistent compile cache: pairing-class kernels take minutes to compile;
# cache across runs (and across warm-up runs before the driver's bench).
from lighthouse_tpu.utils.compile_cache import (  # noqa: E402
    compile_cache_stats,
    enable_compile_cache,
    track_device_compile,
)

enable_compile_cache()


def _partial(**kw):
    """Stream a progress line so a metric killed by the budget still leaves
    its completed per-trial/per-chunk timings behind: the parent collects
    `PARTIAL {...}` lines from the dead subprocess's stdout into the
    combined JSON's errors[metric]["partial"]."""
    print("PARTIAL " + json.dumps(kw), flush=True)


def _span_totals(names):
    """{span: (sum_s, count)} snapshot of the tracing histograms."""
    from lighthouse_tpu.metrics import REGISTRY

    out = {}
    for name in names:
        hist = REGISTRY.histogram(f"trace_span_seconds_{name}")
        out[name] = (hist.sum, hist.count)
    return out


def _span_deltas(before, after):
    """Per-stage mean_ms/samples between two `_span_totals` snapshots
    (stages with no new samples are omitted)."""
    stages = {}
    for name in before:
        d_sum = after[name][0] - before[name][0]
        d_count = after[name][1] - before[name][1]
        if d_count:
            stages[name] = {
                "mean_ms": round(d_sum / d_count * 1000, 2),
                "samples": d_count,
            }
    return stages


def _trials(fn, n=3, label="trial", between=None):
    out = []
    for i in range(n):
        t0 = time.perf_counter()
        fn()
        out.append(time.perf_counter() - t0)
        _partial(**{label: i + 1, "of": n, "s": round(out[-1], 4)})
        if between is not None:
            between()  # untimed inter-trial housekeeping (gc etc.)
    return {
        "median_s": statistics.median(out),
        "min_s": min(out),
        "max_s": max(out),
        "trials": n,
    }


def bench_merkle(jax):
    from lighthouse_tpu.ops.sha256 import (
        bytes_to_words,
        merkle_tree_levels,
        words_to_bytes,
    )

    n_leaves = 1 << 12 if SMOKE else 1 << 20
    rng = np.random.default_rng(7)
    data = rng.integers(0, 256, size=n_leaves * 32, dtype=np.uint8).tobytes()
    leaves = bytes_to_words(data)
    dev_leaves = jax.device_put(leaves)

    def run():
        root_words = merkle_tree_levels(dev_leaves)[0]
        jax.block_until_ready(root_words[0])
        return root_words

    with track_device_compile("merkle_tree_levels"):
        run()  # compile
    t = _trials(run, n=5)

    # host control on a 1/16 slice, extrapolated
    slice_leaves = n_leaves // 16
    slice_data = data[: slice_leaves * 32]

    def host_merkle_root(d):
        nodes = [d[i : i + 32] for i in range(0, len(d), 32)]
        while len(nodes) > 1:
            nodes = [
                hashlib.sha256(nodes[i] + nodes[i + 1]).digest()
                for i in range(0, len(nodes), 2)
            ]
        return nodes[0]

    # pinned trial count; the control's own spread is reported so the
    # vs_baseline trend line carries its noise floor with it
    th = _trials(lambda: host_merkle_root(slice_data), n=3)
    host_s = th["median_s"] * 16

    # correctness spot-check
    got = words_to_bytes(merkle_tree_levels(jax.device_put(bytes_to_words(slice_data)))[0])[:32]
    assert got == host_merkle_root(slice_data), "merkle root mismatch!"

    return {
        "metric": "merkle_tree_hash_1M_leaves",
        "value": round(n_leaves / t["median_s"], 1),
        "unit": "leaves/sec",
        "vs_baseline": round(host_s / t["median_s"], 3),
        "baseline_control": "hashlib on a 1/16 slice x16 (spread below)",
        "spread": t,
        "control_spread": th,
    }


def _make_sets(bls, n_sets, committee):
    """n_sets aggregate-signature sets over one `committee`-key committee.

    The aggregate of per-key signatures on one message equals a single
    signature under the summed secret key (Σ skᵢ·H(m) = (Σ skᵢ)·H(m)), so
    generation costs one host sign per set instead of `committee` — and the
    result is cached on disk so the driver's bench run skips it entirely.
    """
    import pickle

    from lighthouse_tpu.crypto.bls import R

    cache = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        ".bench_cache",
        f"sets_v1_{n_sets}x{committee}.pkl",
    )
    kps = bls.interop_keypairs(committee)
    pks = [kp.pk for kp in kps]  # shared objects: 64 decompressions, not 64k
    msgs = [
        hashlib.sha256(b"att" + i.to_bytes(4, "little")).digest()
        for i in range(n_sets)
    ]
    if os.path.exists(cache):
        with open(cache, "rb") as f:
            sig_bytes = pickle.load(f)
        if len(sig_bytes) == n_sets:
            return [
                bls.SignatureSet(bls.Signature(sb), pks, m)
                for sb, m in zip(sig_bytes, msgs)
            ]
    sk_agg = bls.SecretKey(sum(kp.sk.scalar for kp in kps) % R)
    sets = [
        bls.SignatureSet(sk_agg.sign(m), pks, m) for m in msgs
    ]
    os.makedirs(os.path.dirname(cache), exist_ok=True)
    with open(cache, "wb") as f:
        pickle.dump([s.signature.to_bytes() for s in sets], f)
    return sets


def bench_bls(jax):
    """Metric 1 dispatcher: host MSM+pool lane by default (the lane this
    box can actually complete), device lane opt-in via BENCH_BLS_LANE."""
    if os.environ.get("BENCH_BLS_LANE", "host") == "device":
        return _bench_bls_device(jax)
    return _bench_bls_host(jax)


def _bench_bls_host(jax):
    """Host fast path: one G2 MSM over the RLC'd signatures, bilinearity
    regrouping of the per-set pairings (2 pairs for the gossip-batch
    shape instead of 1025), Miller loops sharded across the fork pool.
    Control = the retained serial per-set loop on a 1/16 subsample,
    extrapolated, in the SAME run (warm caches for both lanes)."""
    from lighthouse_tpu.crypto import bls
    from lighthouse_tpu.parallel import host_pool

    bls.set_backend("host")
    n_sets, committee = (9, 3) if SMOKE else (1024, 64)
    sets = _make_sets(bls, n_sets, committee)
    host = bls._BACKENDS["host"]
    pool = host_pool.get_pool()

    def run():
        assert host.verify_signature_sets(sets, random.Random(5))

    t0 = time.perf_counter()
    run()  # warm: hash_to_g2 + decompression caches fill, pool forks
    warm_s = time.perf_counter() - t0
    _partial(phase="warm", s=round(warm_s, 2))

    _SPANS = ("bls_msm_g2", "bls_parallel_pairing")
    before = _span_totals(_SPANS)
    t = _trials(run, n=3)
    stages = _span_deltas(before, _span_totals(_SPANS))

    # same-run serial control (the pre-MSM per-set loop), subsampled —
    # the full serial run is ~n_sets × 13 ms of wNAF ladders + Miller
    # loops and scales linearly in sets, so a 1/16 slice ×16 is honest
    ctrl_sets = sets[: max(8, n_sets // 16)]

    def ctrl_run():
        assert host.verify_signature_sets_serial(ctrl_sets, random.Random(5))

    th = _trials(ctrl_run, n=3, label="control_trial")
    host_s = th["median_s"] * (n_sets / len(ctrl_sets))

    return {
        "metric": "bls_batch_verify_1k",
        "value": round(n_sets / t["median_s"], 2),
        "unit": "sets/sec",
        "vs_baseline": round(host_s / t["median_s"], 3),
        "baseline_control": (
            "serial per-set RLC loop (pre-MSM host path) on a 1/16 "
            "subsample x16, same run; see BENCH_NOTES.md"
        ),
        "config": {
            "sets": n_sets,
            "committee": committee,
            "lane": "host",
            "pool": pool.size,
            "pool_env": os.environ.get(host_pool.ENV_VAR),
            "warm_s": round(warm_s, 2),
        },
        "spread": t,
        "control_spread": th,
        "stages": stages,
        "cache": bls.cache_stats(),
    }


def _bench_bls_device(jax):
    """Device lane (opt-in): full on-device verifier in bounded-shape
    chunks, reporting a compile-vs-execute split so a timeout in either
    phase still says which phase ate the budget (every per-chunk timing
    streams as a PARTIAL line either way)."""
    from lighthouse_tpu.crypto import bls
    from lighthouse_tpu.ops.bls381_verify import verify_signature_sets_device_full

    bls.set_backend("host")
    # smoke shapes match the device test-suite buckets (16-lane sets,
    # 4-lane committees) so the persistent cache serves every kernel
    n_sets, committee = (9, 3) if SMOKE else (1024, 64)
    # The full 1024-lane kernels compile for hours and the axon remote
    # compiler drops connections on compiles that long — process the
    # batch in identical-shape chunks instead: ONE compile, reused across
    # chunks, with fresh RLC randomness per chunk (the security argument
    # is per-batch). Default DEFAULT_DEVICE_CHUNK (= 32, shared with the
    # node's LIGHTHOUSE_TPU_BLS_CHUNK): the 128-chunk cold compile never
    # fit the bench window in five rounds of trying — a real number at a
    # small chunk beats another timeout at a big one. BENCH_BLS_CHUNK=0
    # restores the single-batch shape.
    chunk = 0 if SMOKE else int(
        os.environ.get("BENCH_BLS_CHUNK", str(bls.DEFAULT_DEVICE_CHUNK))
    )
    sets = _make_sets(bls, n_sets, committee)

    def dev_run(phase="execute"):
        if chunk:
            t0 = time.perf_counter()
            for i in range(0, n_sets, chunk):
                assert verify_signature_sets_device_full(
                    sets[i:i + chunk], random.Random(5 + i)
                )
                _partial(phase=phase, chunk_done=i // chunk + 1,
                         of=(n_sets + chunk - 1) // chunk,
                         elapsed_s=round(time.perf_counter() - t0, 2))
        else:
            assert verify_signature_sets_device_full(sets, random.Random(5))

    t0 = time.perf_counter()
    # compile-vs-execute through the standard metrics path: the warmup
    # rides a device_compile span and the compile_cache_{hits,misses}/
    # compile-seconds counters (reported below), not just phase labels
    with track_device_compile("bls381_verify"):
        dev_run(phase="compile")  # compile + cache warm
    compile_s = time.perf_counter() - t0
    _partial(phase="compile", s=round(compile_s, 2))
    t = _trials(dev_run, n=3)

    # same-run serial host control on a 1/16 slice, extrapolated
    ctrl_sets = sets[: max(8, n_sets // 16)]
    host = bls._BACKENDS["host"]

    def host_run():
        assert host.verify_signature_sets_serial(ctrl_sets, random.Random(5))

    th = _trials(host_run, n=3, label="control_trial")
    host_s = th["median_s"] * (n_sets / len(ctrl_sets))

    return {
        "metric": "bls_batch_verify_1k",
        "value": round(n_sets / t["median_s"], 2),
        "unit": "sets/sec",
        "vs_baseline": round(host_s / t["median_s"], 3),
        "baseline_control": (
            "serial per-set RLC loop (host, no blst in image); "
            "see BENCH_NOTES.md"
        ),
        "config": {"sets": n_sets, "committee": committee, "chunk": chunk,
                   "lane": "device"},
        "compile": {
            "s": round(compile_s, 2),
            "over_execute_s": round(compile_s - t["median_s"], 2),
        },
        "compile_cache": compile_cache_stats(),
        "spread": t,
    }


def bench_pairing(jax):
    """Host microbench for the optimized pairing path: one `pairing_check`
    of 2 pairs — the exact shape of a single signature verification and the
    `vs_baseline` control every device number is scored against. The old
    (reference) path is timed once alongside for the continuity record in
    BENCH_NOTES.md."""
    from lighthouse_tpu.crypto import bls
    from lighthouse_tpu.crypto.bls import cache_stats
    from lighthouse_tpu.crypto.bls12_381 import (
        FQ, G1_GEN, hash_to_g2, pairing_check, pt_neg,
    )
    from lighthouse_tpu.crypto.bls12_381 import pairing_reference

    bls.set_backend("host")
    sk = bls.interop_secret_key(0)
    pk_pt = sk.public_key().point()
    msg = hashlib.sha256(b"pairing microbench").digest()
    h = hash_to_g2(msg)
    sig_pt = sk.sign(msg).point()
    pairs = [(pk_pt, h), (pt_neg(FQ, G1_GEN), sig_pt)]

    def run():
        assert pairing_check(pairs)

    run()  # warm (builds the fixed-base/window tables)
    t = _trials(run, n=5)
    # ≥3-trial median for the control too — a single-trial control made
    # vs_baseline pure noise (BENCH_NOTES "Variance")
    tr = _trials(lambda: pairing_reference.pairing_check(pairs), n=3,
                 label="ref_trial")

    return {
        "metric": "pairing_check_ms",
        "value": round(t["median_s"] * 1000, 2),
        "unit": "ms/check (2 pairs, host)",
        "vs_baseline": round(tr["median_s"] / t["median_s"], 2),
        "baseline_control": "pairing_reference (pre-optimization host path)",
        "reference_ms": round(tr["median_s"] * 1000, 2),
        "spread": t,
        "control_spread": tr,
        "cache": cache_stats(),
    }


def bench_kzg(jax):
    """North-star metric 4: `verify_blob_kzg_proof_batch` on a 6-blob
    Deneb block (crypto/kzg/src/lib.rs:81-107). Device path = fused
    barycentric evaluations (ops/fr) + device multi-pairing; control =
    the same engine with the device disabled (host bigint). Blob set
    generation (12 MSMs) is disk-cached like the BLS sets."""
    import pickle
    import random as _r

    from lighthouse_tpu.crypto.kzg import FR_MODULUS, Kzg, TrustedSetup

    n_blobs = 2 if SMOKE else 6
    if SMOKE:
        setup = TrustedSetup.insecure_dev(64)
        n_domain = 64
    else:
        setup = TrustedSetup.default()
        n_domain = setup.n
    host = Kzg(setup)

    rng = _r.Random(33)
    blobs = [
        b"".join(
            rng.randrange(FR_MODULUS).to_bytes(32, "big")
            for _ in range(n_domain)
        )
        for _ in range(n_blobs)
    ]
    cache = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        ".bench_cache",
        f"kzg_v1_{n_blobs}x{n_domain}.pkl",
    )
    cs = proofs = None
    if os.path.exists(cache):
        with open(cache, "rb") as f:
            cs, proofs = pickle.load(f)
    if cs is None or len(cs) != n_blobs:
        cs = [host.blob_to_kzg_commitment(b) for b in blobs]
        proofs = [host.compute_blob_kzg_proof(b, c) for b, c in zip(blobs, cs)]
        os.makedirs(os.path.dirname(cache), exist_ok=True)
        with open(cache, "wb") as f:
            pickle.dump((cs, proofs), f)

    dev = Kzg(setup, device=True)

    def dev_run():
        assert dev.verify_blob_kzg_proof_batch(blobs, cs, proofs)

    with track_device_compile("kzg_verify_blob_batch"):
        dev_run()  # compile + cache warm
    assert dev._dev is not None, "device KZG fell back to host mid-bench"
    t = _trials(dev_run, n=3)

    def host_run():
        assert host.verify_blob_kzg_proof_batch(blobs, cs, proofs)

    # >=3 trials: a single-trial control made vs_baseline pure noise
    th = _trials(host_run, n=3)

    return {
        "metric": "kzg_verify_blob_batch_6",
        "value": round(t["median_s"] * 1000, 2),
        "unit": "ms/batch (6 blobs)",
        "vs_baseline": round(th["median_s"] / t["median_s"], 3),
        "baseline_control": "host bigint engine, same machine",
        "config": {"blobs": n_blobs, "domain": n_domain},
        "compile_cache": compile_cache_stats(),
        "spread": t,
        "control_spread": th,
    }


def bench_da_verify(jax):
    """PeerDAS cell-proof verification (das/proofs.py): a full block's
    worth of data-column cells collapsed into ONE RLC pairing check whose
    two sides are Pippenger MSMs sharded over the host fork pool.
    Headline: cells/sec through the batched lane at mainnet blob counts
    (6 blobs x 128 columns = 768 cells over the 4096-point domain).
    Control: the per-cell scalar oracle (`verify_cell_kzg_proof`, one
    full pairing check per cell) on a same-run subsample, extrapolated to
    cells/sec — the bench asserts the batched lane's >=5x and checks
    verdict parity on both a clean set and a tampered cell (batch False,
    oracle pinpointing the same cell). Proof GENERATION uses the
    insecure_dev setup's dev-tau fast path (one scalar mul per cell
    instead of a 4096-point quotient MSM); verification never shortcuts —
    the pairing math is identical for every setup, so the measured lane
    is honest."""
    import pickle
    import random as _r

    from lighthouse_tpu.crypto.kzg import FR_MODULUS, Kzg, TrustedSetup
    from lighthouse_tpu.das.proofs import (
        compute_cells_and_proofs,
        verify_cell_kzg_proof,
        verify_cell_kzg_proof_batch,
    )

    if SMOKE:
        n_blobs, n_domain, n_columns, oracle_n = 2, 64, 16, 4
    else:
        n_blobs, n_domain, n_columns, oracle_n = 6, 4096, 128, 24
    kzg = Kzg(TrustedSetup.insecure_dev(n_domain))

    rng = _r.Random(47)
    blobs = [
        b"".join(
            rng.randrange(FR_MODULUS).to_bytes(32, "big")
            for _ in range(n_domain)
        )
        for _ in range(n_blobs)
    ]
    cache = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        ".bench_cache",
        f"da_verify_v1_{n_blobs}x{n_domain}x{n_columns}.pkl",
    )
    sets = None
    if os.path.exists(cache):
        with open(cache, "rb") as f:
            sets = pickle.load(f)
    if sets is None or len(sets) != n_blobs:
        sets = [compute_cells_and_proofs(b, kzg, n_columns) for b in blobs]
        os.makedirs(os.path.dirname(cache), exist_ok=True)
        with open(cache, "wb") as f:
            pickle.dump(sets, f)
    items = [
        (commitment, j, cells[j], proofs[j])
        for cells, proofs, commitment in sets
        for j in range(n_columns)
    ]
    n_cells = len(items)
    _partial(stage="built", cells=n_cells)

    spans = ("da_verify", "da_derive", "da_msm", "da_pairing")
    before = _span_totals(spans)

    def batched_run():
        assert verify_cell_kzg_proof_batch(items, kzg)

    t = _trials(batched_run, n=3)
    stages = _span_deltas(before, _span_totals(spans))

    # same-run control: the per-cell scalar oracle on an evenly spaced
    # subsample, extrapolated to cells/sec
    sub = items[:: max(1, n_cells // oracle_n)][:oracle_n]

    def oracle_run():
        for c, j, cell, proof in sub:
            assert verify_cell_kzg_proof(c, j, cell, proof, kzg)

    tr = _trials(oracle_run, n=2, label="control")

    batched_cps = n_cells / t["median_s"]
    oracle_cps = len(sub) / tr["median_s"]
    speedup = batched_cps / oracle_cps
    floor = 1.5 if SMOKE else 5.0
    assert speedup >= floor, (
        f"batched cell verification only {speedup:.2f}x the scalar "
        f"oracle (floor {floor}x)"
    )

    # verdict parity on a tampered set: batch refuses, oracle pinpoints
    ci, jj, cell, proof = items[n_cells // 2]
    bad = bytearray(cell)
    bad[0] ^= 1
    bad_items = list(items)
    bad_items[n_cells // 2] = (ci, jj, bytes(bad), proof)
    assert not verify_cell_kzg_proof_batch(bad_items, kzg)
    assert not verify_cell_kzg_proof(ci, jj, bytes(bad), proof, kzg)
    assert verify_cell_kzg_proof(*items[0][:2], items[0][2], items[0][3], kzg)

    return {
        "metric": "da_verify",
        "value": round(batched_cps, 1),
        "unit": "cells/s (batched RLC lane)",
        "vs_baseline": round(speedup, 2),
        "baseline_control": (
            f"per-cell scalar oracle, {len(sub)}-cell same-run subsample"
        ),
        "config": {
            "blobs": n_blobs,
            "domain": n_domain,
            "columns": n_columns,
            "cells": n_cells,
            "oracle_cells_per_s": round(oracle_cps, 1),
            "tamper_parity": "passed",
        },
        "stages": stages,
        "spread": t,
        "control_spread": tr,
    }


def bench_da_withholding(jax):
    """The DA withholding-recovery scenario as a first-class bench entry
    (testing/testnet.run_column_withholding_scenario): an adversary
    proposes blob blocks while suppressing erasure-coded columns at
    publish AND over RPC. Sub-50% kept — every honest node's sampling
    fails, the fleet refuses the head and finalizes past it; >=50% kept —
    honest nodes hit the reconstruction threshold, promote to full
    availability, and import. Headline: wall seconds from the recovery
    proposal's heal to finality (the soak-recovery shape); refusal/
    reconstruction counts ride along. The chain-health oracle asserts
    single-head + finality between phases."""
    from dataclasses import replace

    from lighthouse_tpu.testing.testnet import (
        DasTestnetEthSpec,
        run_column_withholding_scenario,
    )
    from lighthouse_tpu.types.chain_spec import minimal_spec

    spec = replace(
        minimal_spec(),
        altair_fork_epoch=0,
        bellatrix_fork_epoch=0,
        capella_fork_epoch=0,
        deneb_fork_epoch=0,
    )
    t0 = time.perf_counter()
    report = run_column_withholding_scenario(
        spec, DasTestnetEthSpec, seed=2026
    )
    total_s = time.perf_counter() - t0
    return {
        "metric": "da_withholding",
        "value": report["recovery_to_finality_s"],
        "unit": "s heal->finality (after >=50% recovery import)",
        "vs_baseline": None,
        "baseline_control": "chain-health oracle invariants (pass/fail)",
        "config": {
            "withheld_refusal": len(report["withheld_refusal"]),
            "sampling_failures": report["sampling_failures"],
            "reconstructions": report["reconstructions"],
            "refusal_recovery_slots": report["refusal_recovery_slots"],
            "recovery_slots": report["recovery_slots"],
            "head_convergence_s": report["head_convergence_s"],
            "scenario_wall_s": round(total_s, 1),
            "seed": report["seed"],
        },
    }


def bench_block_import(jax):
    """North-star metric 5 at harness scale. Runs under whichever BLS
    backend `--bls-backend`/BENCH_BLS_BACKEND selects (default host;
    `tpu` exercises the device verifier the node actually wires), and
    attaches a per-stage span breakdown from the tracing histograms —
    signature_batch_verify is nested inside state_transition, so stages
    overlap rather than sum to the total."""
    from lighthouse_tpu.beacon_chain.harness import BeaconChainHarness
    from lighthouse_tpu.crypto import bls
    from lighthouse_tpu.types.chain_spec import minimal_spec
    from lighthouse_tpu.types.eth_spec import MinimalEthSpec

    backend = os.environ.get("BENCH_BLS_BACKEND", "host")
    bls.set_backend(backend)
    h = BeaconChainHarness(minimal_spec(), MinimalEthSpec, validator_count=64)

    _STAGES = (
        "signature_batch_verify",
        "signature_set_assembly",
        "bls_rlc_accumulate",
        "bls_hash_to_g2",
        "bls_msm_g2",
        "bls_pairing",
        "bls_parallel_pairing",
        "state_transition",
        "fork_choice_on_block",
    )
    before = _span_totals(_STAGES)
    times = []
    for _ in range(8):
        slot = h.chain.head_state.slot + 1
        h.slot_clock.set_slot(slot)
        t0 = time.perf_counter()
        h.add_block_at_slot(slot)
        times.append(time.perf_counter() - t0)
        h.attest_to_head(slot)
    stages = _span_deltas(before, _span_totals(_STAGES))
    from lighthouse_tpu.crypto.bls import cache_stats

    return {
        "metric": "block_import_ms",
        "value": round(statistics.median(times) * 1000, 2),
        "unit": "ms/block (produce+sign+import)",
        "config": {
            "validators": 64,
            "spec": "minimal",
            "blocks": len(times),
            "backend": backend,
        },
        "stages": stages,
        "cache": cache_stats(),
    }


def bench_block_production(jax):
    """Proposer pipeline (north-star 5 at registry scale): unsigned-block
    production at 1M validators across an epoch boundary, cold (the
    advance to the proposal slot — an epoch transition — paid inline on
    the hot path) vs pre-advanced (the StateAdvanceTimer already built
    the boundary state off-path; production starts from the cached CoW
    snapshot). Stage means come from the `block_production` trace-root
    histograms: `advance` collapses in the pre-advanced runs while
    `pack`/`assemble` are invariant."""
    import gc

    from lighthouse_tpu.beacon_chain.harness import BeaconChainHarness
    from lighthouse_tpu.beacon_chain.state_advance import StateAdvanceTimer
    from lighthouse_tpu.metrics import REGISTRY
    from lighthouse_tpu.state_processing import per_slot_processing

    n = 5_000 if SMOKE else 1_000_000
    # the boundary-ready 1M Altair fixture the epoch bench uses:
    # randomized participation/scores one slot shy of a REAL epoch
    # boundary (3*SPE-1 — justification and rewards run in full, unlike
    # the skipped-work genesis boundary)
    st, spec, E = _build_epoch_state(n, resident=True)
    # the cloned-registry fixture keeps only validator 0's pubkey: re-seat
    # the sync committees from the cloned registry so the assemble stage's
    # sync-aggregate processing resolves every committee pubkey
    from lighthouse_tpu.state_processing.altair import get_next_sync_committee

    sc = get_next_sync_committee(st, E)
    st.current_sync_committee = sc
    st.next_sync_committee = sc.copy()
    st.hash_tree_root()  # commit caches: trials measure increments
    h = BeaconChainHarness(spec, E, validator_count=8)
    chain = h.chain
    # graft the fixture in as the head's state: production reads the
    # parent state by root, so the head root must be the fixture's own
    # header root (what process_block_header will check parent against)
    tmp = st.copy()
    per_slot_processing(tmp, spec, E)  # untimed: fills the header's state root
    parent_root = tmp.latest_block_header.hash_tree_root()
    del tmp
    gc.collect()
    chain.head_root = parent_root
    chain._states[parent_root] = st
    slot = int(st.slot) + 1
    h.slot_clock.set_slot(slot)
    reveal = b"\x5c" * 96  # NO_VERIFICATION production: any 96 bytes

    _STAGES = ("block_production", "advance", "pack", "assemble")

    def cold():
        chain.state_advance_cache.clear()
        chain.produce_block_on_state(slot, reveal)

    cold()  # untimed warmup: one-time caches (pubkey hints, shuffling)
    gc.collect()
    before = _span_totals(_STAGES)
    t_cold = _trials(cold, n=3, label="cold_trial", between=gc.collect)
    cold_stages = _span_deltas(before, _span_totals(_STAGES))

    timer = StateAdvanceTimer(chain)
    chain.state_advance_cache.clear()
    timer._advance(slot - 1)  # the slot-tail pre-advance, off the timed path
    hits = REGISTRY.counter("state_advance_hits_total").value()

    def pre_advanced():
        chain.produce_block_on_state(slot, reveal)

    before = _span_totals(_STAGES)
    t_pre = _trials(pre_advanced, n=3, label="pre_advanced_trial",
                    between=gc.collect)
    pre_stages = _span_deltas(before, _span_totals(_STAGES))
    assert REGISTRY.counter("state_advance_hits_total").value() > hits

    speedup = t_cold["median_s"] / t_pre["median_s"]
    if not SMOKE:
        # acceptance: the pre-advance absorbs the boundary transition
        assert speedup >= 5.0, (
            f"pre-advanced production only {speedup:.1f}x faster than cold"
        )
    return {
        "metric": "block_production_ms",
        "value": round(t_pre["median_s"] * 1000, 2),
        "unit": "ms/block (pre-advanced, epoch boundary, 1M validators)",
        "config": {
            "validators": n,
            "spec": "minimal",
            "slot": slot,
            "trials": 3,
        },
        "details": {
            "cold_ms": round(t_cold["median_s"] * 1000, 2),
            "pre_advanced_ms": round(t_pre["median_s"] * 1000, 2),
            "speedup": round(speedup, 2),
            "cold_stages": cold_stages,
            "pre_advanced_stages": pre_stages,
        },
        "spread": t_pre,
        "control_spread": t_cold,
    }


def _build_1m_state(n: int):
    """The shared 1M-registry fixture: interop genesis + cloned registry,
    converted to the node's tree-states representation."""
    from dataclasses import replace

    from lighthouse_tpu.beacon_chain.chain import _make_persistent
    from lighthouse_tpu.crypto import bls
    from lighthouse_tpu.state_processing import interop_genesis_state
    from lighthouse_tpu.types.chain_spec import minimal_spec
    from lighthouse_tpu.types.eth_spec import MinimalEthSpec as E

    bls.set_backend("fake_crypto")
    spec = replace(minimal_spec(), altair_fork_epoch=0)
    state = interop_genesis_state(
        bls.interop_keypairs(8), 1_600_000_000, b"\x42" * 32, spec, E
    )
    v0 = state.validators[0]
    vs, bal = [], []
    for i in range(n):
        v = v0.copy()
        v.withdrawal_credentials = i.to_bytes(32, "little")
        vs.append(v)
        bal.append(32_000_000_000)
    state.validators = vs
    state.balances = bal
    # the node's tree-states representation: structurally-shared registry
    # (PersistentContainerList) + balance blocks + resident columns —
    # what block import uses (the node attaches columns at its first
    # epoch transition; re-roots then serve element roots from them)
    _make_persistent(state)
    from lighthouse_tpu.state_processing.registry_columns import (
        registry_columns_for,
    )

    registry_columns_for(state).refresh(state)
    return state, vs


def bench_state_root(jax):
    """North-star metric 2: `hash_tree_root` of a BeaconState at 1M
    validators — the per-slot incremental update (a block's worth of
    mutations re-rooted through the dirty-index caches), with the cold
    columnar full-build promoted to a first-class number (median + spread
    over fresh-cache rebuilds). Control = this state's root via the plain
    non-cached recompute path."""
    import random as _r

    from lighthouse_tpu.types.eth_spec import MinimalEthSpec as E
    from lighthouse_tpu.utils.sha256_batch import batch_mode

    n = 5_000 if SMOKE else 1_000_000
    state, vs = _build_1m_state(n)
    rng = _r.Random(11)

    # cold build: fresh state-level cache each trial (the registry's
    # columnar batched pass end to end — no memos, no committed layers)
    def cold_build():
        state.__dict__.pop("_thc_cache", None)
        return state.hash_tree_root()

    t_cold = _trials(cold_build, n=3, label="cold_trial")
    root = state.hash_tree_root()

    t_copy0 = time.perf_counter()
    state_copy = state.copy()  # O(#blocks) structural share + CoW layers
    copy_s = time.perf_counter() - t_copy0
    assert state_copy.hash_tree_root() == root

    def mutate_and_root():
        # a block's worth of churn: ~128 attesting balance changes + a
        # couple of validator-record updates (CoW mutation discipline)
        for _ in range(128):
            i = rng.randrange(n)
            state.balances[i] = int(state.balances[i]) + 1
        for _ in range(2):
            v = state.validators.mutate(rng.randrange(n))
            v.effective_balance = int(v.effective_balance) + 1
        return state.hash_tree_root()

    mutate_and_root()  # first update un-shares the CoW layers once
    t = _trials(mutate_and_root, n=5)

    # control: the same state through the NON-cached recompute path,
    # measured on a 1/64 slice and extrapolated (a full recompute at 1M
    # is minutes — exactly the point of the cache)
    from lighthouse_tpu.ssz.core import List as SszList
    from lighthouse_tpu.types.containers import build_types

    ctrl_cls = SszList[build_types(E).Validator, E.VALIDATOR_REGISTRY_LIMIT]
    ctrl_slice = vs[: max(1, n // 64)]
    ctrl_cls.hash_tree_root_of(ctrl_slice)  # warm-up: exclude compiles
    t_ctrl = _trials(lambda: ctrl_cls.hash_tree_root_of(ctrl_slice), n=1)
    control_s = t_ctrl["median_s"] * 64

    return {
        "metric": "state_root_update_1m",
        "value": round(t["median_s"] * 1000, 2),
        "unit": "ms/update (128-balance + 2-validator churn, re-root)",
        "vs_baseline": round(control_s / t["median_s"], 2),
        "baseline_control": "non-cached registry recompute (1/64 slice x64)",
        "cold_build": {
            "value": round(t_cold["median_s"], 2),
            "unit": "s/cold columnar build",
            "spread": t_cold,
        },
        "config": {
            "validators": n,
            "cold_build_s": round(t_cold["median_s"], 2),
            "state_copy_ms": round(copy_s * 1000, 2),
            "sha256_batch_mode": batch_mode(),
        },
        "spread": t,
    }


def bench_epoch_reroot(jax):
    """Epoch-boundary re-root at 1M validators: the effective-balance
    sweep dirties ~a third of the registry. Since PR 6 the container
    list's dirty cap (1<<20) keeps the index set exact at this scale, so
    the re-root is a 333k-row sparse update whose element roots come
    straight from the resident columns — no Python object extraction
    (the r05 path overflowed to a full 7M-hash columnar rebuild:
    14.7 s)."""
    n = 5_000 if SMOKE else 1_000_000
    state, _ = _build_1m_state(n)
    state.hash_tree_root()  # commit the caches (cold build)
    eb = [31_000_000_000, 32_000_000_000]

    def churn_and_reroot():
        # mass effective-balance churn: every 3rd validator flips
        for i in range(0, n, 3):
            v = state.validators.mutate(i)
            v.effective_balance = eb[0]
        eb.reverse()
        return state.hash_tree_root()

    t = _trials(churn_and_reroot, n=2)
    return {
        "metric": "epoch_boundary_reroot_1m",
        "value": round(t["median_s"], 2),
        "unit": "s/re-root (n/3 effective-balance churn, sparse columnar path)",
        "config": {"validators": n, "churned": (n + 2) // 3},
        "spread": t,
    }


def _build_epoch_state(n: int, resident: bool):
    """A boundary-ready Altair state of `n` cloned validators with
    randomized participation/scores and steady-state balances (inside
    the hysteresis band: real epochs move balances by rewards, not by
    mass effective-balance churn). `resident` converts to the node's
    tree-states representation and pre-warms the columns (the one-time
    cold build the bench excludes, exactly like the hash caches')."""
    import random as _r
    from dataclasses import replace

    from lighthouse_tpu.crypto import bls
    from lighthouse_tpu.state_processing import interop_genesis_state
    from lighthouse_tpu.types.chain_spec import minimal_spec
    from lighthouse_tpu.types.eth_spec import MinimalEthSpec as E

    bls.set_backend("fake_crypto")
    spec = replace(minimal_spec(), altair_fork_epoch=0)
    base = interop_genesis_state(
        bls.interop_keypairs(8), 1_600_000_000, b"\x42" * 32, spec, E
    )
    # clone validator 0 out to n (deposit-path construction of n keys is
    # minutes of BLS; registry shape is what the sweep cares about)
    rng = _r.Random(3)
    v0 = base.validators[0]
    vs, bal, prev, cur, scores = [], [], bytearray(n), bytearray(n), []
    for i in range(n):
        v = v0.copy()
        v.withdrawal_credentials = i.to_bytes(32, "little")
        vs.append(v)
        # inside the hysteresis band around the 32-ETH effective balance
        bal.append(32_000_000_000 + rng.randrange(1_000_000_000))
        prev[i] = rng.randrange(8)
        cur[i] = rng.randrange(8)
        scores.append(rng.randrange(4))
    base.validators = vs
    base.balances = bal
    base.previous_epoch_participation = prev
    base.current_epoch_participation = cur
    base.inactivity_scores = scores
    base.slot = 3 * E.SLOTS_PER_EPOCH - 1
    if resident:
        from lighthouse_tpu.beacon_chain.chain import _make_persistent
        from lighthouse_tpu.state_processing.registry_columns import (
            registry_columns_for,
        )

        _make_persistent(base)
        cols = registry_columns_for(base)
        if cols is not None:  # None under LIGHTHOUSE_TPU_RESIDENT_COLUMNS=0
            cols.refresh(base)
    return base, spec, E


_EPOCH_STAGE_SPANS = tuple(
    f"epoch_stage_{s}"
    for s in (
        "columns_refresh",
        "justification",
        "inactivity",
        "rewards",
        "registry_updates",
        "slashings",
        "effective_balances",
        "final_updates",
    )
)


def _epoch_metric(jax, n: int, metric: str, trials: int, control_trials: int,
                  control_fraction: int):
    """Shared body of the epoch-transition metrics: resident-columns
    trials with a per-stage span breakdown and a zero-rebuild check,
    plus a same-run per-validator-oracle control
    (state_processing/epoch_reference.process_epoch_reference — the
    retained scalar spec-loop implementation, bit-identical by the
    differential suite) on a 1/`control_fraction` subsample, scaled.
    The r05 legacy snapshot path is also timed once on the subsample
    (`legacy_snapshot` in the JSON) for metric continuity."""
    import gc

    from lighthouse_tpu.metrics import REGISTRY
    from lighthouse_tpu.state_processing.epoch_reference import (
        process_epoch_reference,
    )
    from lighthouse_tpu.state_processing.per_epoch import process_epoch

    # the metric MEASURES residency: neutralize an inherited process-wide
    # opt-out for the trial phases, restoring it afterwards (the legacy
    # continuity timing below sets it explicitly either way)
    prior_resident = os.environ.pop("LIGHTHOUSE_TPU_RESIDENT_COLUMNS", None)

    state, spec, E = _build_epoch_state(n, resident=True)
    copies = [state.copy() for _ in range(trials)]

    def rebuild_counts():
        c = REGISTRY.counter("registry_columns_rebuilds_total")
        return {k[0][1]: v for k, v in c.values().items()}

    before_rebuilds = rebuild_counts()
    spans_before = _span_totals(_EPOCH_STAGE_SPANS)

    def run():
        process_epoch(copies.pop(), spec, E)  # copy cost excluded

    # gc BETWEEN trials (untimed): the consumed state must not skew the
    # next trial, but a full-heap collection over a 1M-object registry is
    # not epoch-transition time
    t = _trials(run, n=trials, between=gc.collect)
    stages = _span_deltas(spans_before, _span_totals(_EPOCH_STAGE_SPANS))
    rebuild_delta = {
        k: v - before_rebuilds.get(k, 0) for k, v in rebuild_counts().items()
    }
    del state, copies
    gc.collect()

    # same-run per-validator-oracle control on a plain-list subsample
    # (the oracle is representation-agnostic scalar Python; plain lists
    # keep it free of any machinery under test)
    ctrl_n = max(1000, n // control_fraction)
    ctrl_state, ctrl_spec, _ = _build_epoch_state(ctrl_n, resident=False)
    ctrl_copies = [ctrl_state.copy() for _ in range(control_trials)]

    def ctrl_run():
        process_epoch_reference(ctrl_copies.pop(), ctrl_spec, E)

    th = _trials(
        ctrl_run, n=control_trials, label="control_trial", between=gc.collect
    )
    control_s = th["median_s"] * (n / ctrl_n)

    # continuity: the r05 legacy snapshot path (vectorized over
    # per-epoch object snapshots), one timing on the same subsample
    os.environ["LIGHTHOUSE_TPU_RESIDENT_COLUMNS"] = "0"
    try:
        legacy_state, legacy_spec, _ = _build_epoch_state(
            ctrl_n, resident=True
        )
        t0 = time.perf_counter()
        process_epoch(legacy_state, legacy_spec, E)
        legacy_s = time.perf_counter() - t0
    finally:
        if prior_resident is None:
            del os.environ["LIGHTHOUSE_TPU_RESIDENT_COLUMNS"]
        else:
            os.environ["LIGHTHOUSE_TPU_RESIDENT_COLUMNS"] = prior_resident
    del ctrl_state, ctrl_copies, legacy_state
    gc.collect()

    return {
        "metric": metric,
        "value": round(t["median_s"] * 1000, 1),
        "unit": f"ms/epoch ({n} validators, minimal preset)",
        "vs_baseline": round(control_s / t["median_s"], 3),
        "baseline_control": (
            "per-validator oracle (epoch_reference.process_epoch_reference, "
            f"scalar spec loops) on a 1/{control_fraction} subsample "
            f"x{control_fraction}, same run"
        ),
        "config": {
            "validators": n,
            "control_validators": ctrl_n,
            "steady_state_column_rebuilds": rebuild_delta,
            "legacy_snapshot_subsample_ms": round(legacy_s * 1000, 1),
            "legacy_snapshot_scaled_ms": round(
                legacy_s * (n / ctrl_n) * 1000, 1
            ),
        },
        "stages": stages,
        "spread": t,
        "control_spread": th,
    }


def bench_epoch_transition(jax):
    """Altair epoch sweep at 100k validators over the resident columnar
    registry (kept alongside epoch_transition_1m for vs_baseline
    history; r01-r05 measured the legacy snapshot path on plain lists —
    see BENCH_NOTES.md for the continuity note)."""
    n = 2_000 if SMOKE else 100_000
    return _epoch_metric(
        jax, n, "epoch_transition_100k", trials=3, control_trials=3,
        control_fraction=8,
    )


def bench_epoch_transition_1m(jax):
    """THE tentpole metric: full epoch transition at 1M validators on
    the state-resident columnar registry — zero column rebuilds in
    steady state (counter-asserted in the JSON), every sweep an array
    program, writebacks as vectorized diffs."""
    n = 20_000 if SMOKE else 1_000_000
    return _epoch_metric(
        jax, n, "epoch_transition_1m", trials=3, control_trials=3,
        control_fraction=16,
    )


def _build_attestation_block(n: int, atts_per_committee: int):
    """A mainnet-shaped attestation batch: an altair tree-states state of
    `n` cloned validators (committee size ≈ mainnet's ~450 at 1M) plus a
    block's worth of valid previous-epoch attestations — every
    (slot, committee) pair of the previous epoch × `atts_per_committee`
    random aggregation patterns (the duplicate-attester fold is
    exercised, exactly like real aggregates)."""
    import random as _r

    from lighthouse_tpu.state_processing.accessors import (
        committee_cache_at,
        get_previous_epoch,
    )
    from lighthouse_tpu.types.containers import build_types
    from lighthouse_tpu.types.eth_spec import MinimalEthSpec as E

    state, spec, _ = _build_epoch_state(n, resident=True)
    state.slot = int(state.slot) + 1  # epoch start: all delays includable
    t = build_types(E)
    rng = _r.Random(11)
    prev = get_previous_epoch(state, E)
    cc = committee_cache_at(state, prev, E)
    atts = []
    for slot in range(prev * E.SLOTS_PER_EPOCH, (prev + 1) * E.SLOTS_PER_EPOCH):
        for index in range(cc.committees_per_slot):
            committee = cc.committee_array(slot, index)
            for _ in range(atts_per_committee):
                bits = [rng.random() < 0.7 for _ in range(committee.size)]
                if not any(bits):
                    bits[0] = True
                atts.append(
                    t.Attestation(
                        aggregation_bits=bits,
                        data=t.AttestationData(
                            slot=slot,
                            index=index,
                            beacon_block_root=state.block_roots[
                                slot % E.SLOTS_PER_HISTORICAL_ROOT
                            ],
                            source=state.previous_justified_checkpoint,
                            target=t.Checkpoint(
                                epoch=prev,
                                root=state.block_roots[
                                    (prev * E.SLOTS_PER_EPOCH)
                                    % E.SLOTS_PER_HISTORICAL_ROOT
                                ],
                            ),
                        ),
                        signature=b"\x00" * 96,
                    )
                )
    return state, spec, atts


def bench_attestation_batch(jax):
    """The block-import hot path PRs 3-6 never touched: apply a block's
    worth of attestations (participation scatter + proposer rewards).
    Columnar pipeline (attestation_batch.process_attestations) vs the
    retained scalar oracle (process_attestations_reference), same
    attestations, fresh state copies, same run."""
    import gc

    from lighthouse_tpu.metrics import REGISTRY
    from lighthouse_tpu.state_processing.attestation_batch import (
        process_attestations,
        process_attestations_reference,
    )
    from lighthouse_tpu.state_processing.per_block import ConsensusContext
    from lighthouse_tpu.types.eth_spec import MinimalEthSpec as E

    n = 2_000 if SMOKE else 16_384  # committee ≈ 512 ≈ mainnet shape
    per_committee = 1 if SMOKE else 4  # 8 slots × 4 committees × 4 = 128
    state, spec, atts = _build_attestation_block(n, per_committee)
    from lighthouse_tpu.state_processing.accessors import (
        committee_cache_at,
        get_previous_epoch,
    )
    from lighthouse_tpu.types.containers import build_types

    fork = build_types(E).fork_of_state(state)
    proposer = 0

    def fresh_ctxt():
        ctxt = ConsensusContext(state.slot)
        ctxt.set_proposer_index(proposer)
        return ctxt

    def warm(s):
        # a node imports blocks against states whose epoch shuffling is
        # already cached; pre-build it (both paths get the same warmup)
        committee_cache_at(s, get_previous_epoch(s, E), E)
        return s

    trials = 3
    copies = [warm(state.copy()) for _ in range(trials + 1)]

    def run():
        process_attestations(
            copies.pop(), atts, spec, E, False, fresh_ctxt(), fork
        )

    before = REGISTRY.counter("attestation_batch_total").values().copy()
    spans_before = _span_totals(("attestation_apply",))
    t = _trials(run, n=trials, between=gc.collect)
    stages = _span_deltas(spans_before, _span_totals(("attestation_apply",)))
    after = REGISTRY.counter("attestation_batch_total").values()

    # differential check rides the bench: batched and scalar end states
    # must agree bit-for-bit on participation and balances
    batched = copies.pop()
    process_attestations(batched, atts, spec, E, False, fresh_ctxt(), fork)
    oracle = warm(state.copy())
    ctrl_times = []
    for i in range(2):
        ctrl_state = oracle if i == 0 else warm(state.copy())
        t0 = time.perf_counter()
        process_attestations_reference(
            ctrl_state, atts, spec, E, False, fresh_ctxt(), fork
        )
        ctrl_times.append(time.perf_counter() - t0)
        _partial(control_trial=i + 1, of=2, s=round(ctrl_times[-1], 4))
    assert bytes(batched.previous_epoch_participation) == bytes(
        oracle.previous_epoch_participation
    ), "batched vs scalar participation mismatch"
    assert list(batched.balances) == list(oracle.balances), (
        "batched vs scalar proposer reward mismatch"
    )

    ctrl = statistics.median(ctrl_times)
    return {
        "metric": "attestation_batch_ms",
        "value": round(t["median_s"] * 1000, 2),
        "unit": f"ms/block ({len(atts)} attestations, {n} validators)",
        "vs_baseline": round(ctrl / t["median_s"], 2),
        "baseline_control": (
            "retained scalar loop (process_attestations_reference), same "
            "attestations + fresh state copies, same run"
        ),
        "config": {
            "validators": n,
            "attestations": len(atts),
            "scalar_ms": round(ctrl * 1000, 2),
            "differential_check": "passed",
            "path_counters": {
                k[0][1]: v - before.get(k, 0) for k, v in after.items()
            },
        },
        "stages": stages,
        "spread": t,
    }


def _hist_percentiles(buckets, counts, qs=(0.5, 0.9, 0.99)):
    """Approximate quantiles from cumulative histogram buckets (linear
    interpolation inside the landing bucket; the +Inf bucket reports the
    last finite bound). `counts` is per-bucket (non-cumulative)."""
    total = sum(counts)
    if total == 0:
        return None
    out = {}
    for q in qs:
        target = q * total
        cum = 0.0
        value = buckets[-1]
        for i, c in enumerate(counts):
            prev_cum = cum
            cum += c
            if cum >= target:
                lo = 0.0 if i == 0 else buckets[i - 1]
                hi = buckets[i] if i < len(buckets) else buckets[-1]
                frac = (target - prev_cum) / c if c else 1.0
                value = lo + (hi - lo) * frac
                break
        out[f"p{int(q * 100)}_ms"] = round(value * 1000.0, 3)
    out["count"] = total
    return out


def _hist_snapshot(prefix: str):
    """Per-WorkType (buckets, counts) of one beacon_processor histogram
    family — PR 9's queue observability, consumed as before/after deltas
    so a bench reports only ITS OWN traffic."""
    from lighthouse_tpu.beacon_processor import WorkType
    from lighthouse_tpu.metrics import REGISTRY

    out = {}
    for t in WorkType:
        kind = t.name.lower()
        buckets, counts, _total, _sum = REGISTRY.histogram(
            prefix + kind
        ).snapshot()
        out[kind] = (buckets, counts)
    return out


def _queue_wait_snapshot():
    """Time-in-queue (submit → worker pickup) per WorkType."""
    return _hist_snapshot("beacon_processor_queue_wait_seconds_")


def _queue_wait_percentiles(before, after):
    """kind -> {p50_ms, p90_ms, p99_ms, count} for every WorkType whose
    queue saw traffic between the two snapshots."""
    out = {}
    for kind, (buckets, counts) in after.items():
        b_counts = before.get(kind, (buckets, [0] * len(counts)))[1]
        delta = [a - b for a, b in zip(counts, b_counts)]
        p = _hist_percentiles(buckets, delta)
        if p is not None:
            out[kind] = p
    return out


def bench_sync_catchup(jax):
    """Sync-engine catch-up rate: blocks/sec for a fresh node pulling N
    slots from a loopback peer through the batch state machine
    (network/sync/range_sync), with the old sequential single-peer loop
    (`sequential_sync_with`, retained in-tree) as the same-run
    vs_baseline control. The range-sync retry/failure counters ride
    along in the JSON so a fault-free run proves itself fault-free —
    and a faulty one shows its retries."""
    from dataclasses import replace

    from lighthouse_tpu.beacon_chain.harness import BeaconChainHarness
    from lighthouse_tpu.crypto import bls
    from lighthouse_tpu.metrics import REGISTRY
    from lighthouse_tpu.network import NetworkService
    from lighthouse_tpu.types.chain_spec import minimal_spec
    from lighthouse_tpu.types.eth_spec import MinimalEthSpec as E

    bls.set_backend("fake_crypto")  # measures the sync engine, not BLS
    spec = replace(minimal_spec(), altair_fork_epoch=0)
    slots = 2 * E.SLOTS_PER_EPOCH if SMOKE else 8 * E.SLOTS_PER_EPOCH
    serve = BeaconChainHarness(spec, E, validator_count=16)
    serve.extend_chain(slots, attest=False)
    na = NetworkService(serve.chain, heartbeat_interval=None).start()

    def one_catchup(method):
        b = BeaconChainHarness(spec, E, validator_count=16)
        nb = NetworkService(b.chain, heartbeat_interval=None).start()
        try:
            b.slot_clock.set_slot(serve.chain.head_state.slot)
            peer = nb.connect("127.0.0.1", na.port)
            t0 = time.perf_counter()
            imported = getattr(nb.sync, method)(peer)
            dt = time.perf_counter() - t0
            assert imported == slots, f"{method} imported {imported}/{slots}"
            return dt
        finally:
            nb.stop()

    def counters():
        return {
            name: REGISTRY.counter(name).value(chain="range")
            for name in (
                "sync_batch_downloads_total",
                "sync_batch_retries_total",
                "sync_batch_failures_total",
            )
        }

    def spread(samples):
        return {
            "median_s": statistics.median(samples),
            "min_s": min(samples),
            "max_s": max(samples),
            "trials": len(samples),
        }

    before = counters()
    queue_before = _queue_wait_snapshot()
    engine, serial = [], []
    for i in range(3):
        engine.append(one_catchup("sync_with"))
        _partial(trial=i + 1, of=3, s=round(engine[-1], 4))
    after = counters()
    queue_wait = _queue_wait_percentiles(queue_before, _queue_wait_snapshot())
    for i in range(3):
        serial.append(one_catchup("sequential_sync_with"))
        _partial(control_trial=i + 1, of=3, s=round(serial[-1], 4))
    na.stop()
    med = statistics.median(engine)
    med_serial = statistics.median(serial)
    return {
        "metric": "sync_catchup",
        "value": round(slots / med, 1),
        "unit": "blocks/sec (two-node loopback catch-up, batch state machine)",
        "vs_baseline": round(med_serial / med, 3),
        "baseline_control": "pre-engine sequential single-peer sync loop, same run",
        "config": {"slots": slots, "validators": 16, "spec": "minimal"},
        "counters": {k: after[k] - before[k] for k in after},
        # PR 9 queue observability: time-in-queue percentiles per WorkType
        # across the engine trials (chain_segment is the sync lane) — the
        # backpressure number the blocks/sec headline can't show
        "queue_wait": queue_wait,
        "spread": spread(engine),
        "control_spread": spread(serial),
    }


def _work_run_snapshot():
    """Handler wall time per WorkType — the import-latency side of the
    queue story."""
    return _hist_snapshot("beacon_processor_work_seconds_")


def bench_gossip_soak(jax):
    """Event-driven node under storm: N faulty peers sustain an
    attestation + aggregate flood (decodable, unknown-root — the worst
    honest-looking spam) at a fresh node WHILE it range-syncs the full
    chain from an honest peer. Headline: catch-up blocks/sec under
    flood; vs_baseline is the fraction of the same run's flood-free
    catch-up rate retained (1.0 = the flood cost nothing). The JSON
    carries the robustness evidence: drop counts (processor backpressure
    + reprocess caps — shed, not hung), queue-wait AND handler-run
    percentiles per WorkType lane, and the reprocess counters."""
    import threading
    from dataclasses import replace

    from lighthouse_tpu.beacon_chain.harness import BeaconChainHarness
    from lighthouse_tpu.crypto import bls
    from lighthouse_tpu.metrics import REGISTRY
    from lighthouse_tpu.network import NetworkService
    from lighthouse_tpu.types.chain_spec import minimal_spec
    from lighthouse_tpu.types.eth_spec import MinimalEthSpec as E

    bls.set_backend("fake_crypto")  # measures the pipeline, not BLS
    spec = replace(minimal_spec(), altair_fork_epoch=0)
    slots = 2 * E.SLOTS_PER_EPOCH if SMOKE else 4 * E.SLOTS_PER_EPOCH
    flooders = 2
    serve = BeaconChainHarness(spec, E, validator_count=16)
    serve.extend_chain(slots, attest=False)
    na = NetworkService(serve.chain, heartbeat_interval=None).start()
    tip = serve.chain.head_state.slot
    template = serve.make_unaggregated_attestations(
        tip, serve.chain.head_root
    )[0]
    t = serve.chain.types
    garbage_roots = [bytes([0x70 + j]) * 32 for j in range(8)]

    def one_catchup(flood: bool):
        b = BeaconChainHarness(spec, E, validator_count=16)
        nb = NetworkService(b.chain, heartbeat_interval=None).start()
        nfs = []
        stop_flood = threading.Event()
        sent = [0]

        def flood_loop(nf, lane):
            i = 0
            while not stop_flood.is_set():
                att = template.copy()
                att.data.beacon_block_root = garbage_roots[
                    i % len(garbage_roots)
                ]
                att.signature = (lane * (1 << 40) + i).to_bytes(
                    8, "little"
                ) + bytes(88)
                if i % 4 == 3:
                    agg = t.SignedAggregateAndProof(
                        message=t.AggregateAndProof(
                            aggregator_index=0,
                            aggregate=att,
                            selection_proof=b"\x01" * 96,
                        ),
                        signature=b"\x02" * 96,
                    )
                    nf.gossip.publish(nf.topic_aggregate, agg.serialize())
                else:
                    nf.gossip.publish(
                        nf.topic_att, t.Attestation.serialize_value(att)
                    )
                sent[0] += 1
                i += 1
                time.sleep(0.001)  # sustained flood, not a GIL vice

        threads = []
        try:
            b.slot_clock.set_slot(tip)
            peer = nb.connect("127.0.0.1", na.port)
            if flood:
                for lane in range(flooders):
                    h = BeaconChainHarness(spec, E, validator_count=16)
                    nf = NetworkService(h.chain, heartbeat_interval=None).start()
                    nf.connect("127.0.0.1", nb.port)
                    nfs.append(nf)
                threads = [
                    threading.Thread(
                        target=flood_loop, args=(nf, lane), daemon=True
                    )
                    for lane, nf in enumerate(nfs)
                ]
                for th in threads:
                    th.start()
            t0 = time.perf_counter()
            imported = nb.sync.sync_with(peer)
            dt = time.perf_counter() - t0
            assert imported == slots, f"imported {imported}/{slots}"
            return dt, sent[0]
        finally:
            stop_flood.set()
            for th in threads:
                th.join(timeout=5)
            for nf in nfs:
                nf.stop()
            nb.stop()

    def counters():
        out = {}
        for name, labels in (
            ("reprocess_held_total", {}),
            ("reprocess_drained_total", {}),
            ("reprocess_expired_total", {"reason": "root_cap"}),
            ("reprocess_expired_total", {"reason": "total_cap"}),
            ("reprocess_expired_total", {"reason": "shutdown"}),
            ("gossip_ignored_total", {}),
            ("gossip_internal_error_total", {}),
        ):
            key = name + (
                f"[{next(iter(labels.values()))}]" if labels else ""
            )
            out[key] = REGISTRY.counter(name).value(**labels)
        for kind in ("gossip_attestation", "gossip_aggregate"):
            out[f"dropped[{kind}]"] = REGISTRY.counter(
                "beacon_processor_dropped_total"
            ).value(kind=kind)
        return out

    def spread(samples):
        return {
            "median_s": statistics.median(samples),
            "min_s": min(samples),
            "max_s": max(samples),
            "trials": len(samples),
        }

    before = counters()
    qw_before, run_before = _queue_wait_snapshot(), _work_run_snapshot()
    flood_times, flood_sent = [], 0
    try:
        for i in range(3):
            dt, sent = one_catchup(flood=True)
            flood_times.append(dt)
            flood_sent += sent
            _partial(trial=i + 1, of=3, s=round(dt, 4), flood_msgs=sent)
        after = counters()
        queue_wait = _queue_wait_percentiles(qw_before, _queue_wait_snapshot())
        handler_run = _queue_wait_percentiles(run_before, _work_run_snapshot())
        clean_times = []
        for i in range(3):
            dt, _ = one_catchup(flood=False)
            clean_times.append(dt)
            _partial(control_trial=i + 1, of=3, s=round(dt, 4))
    finally:
        # a failed trial must not leak the serve node's server/worker
        # threads into the rest of the bench process
        na.stop()
    med, med_clean = statistics.median(flood_times), statistics.median(clean_times)
    return {
        "metric": "gossip_soak",
        "value": round(slots / med, 1),
        "unit": (
            f"blocks/sec (range sync under attestation/aggregate flood, "
            f"{flooders} faulty peers)"
        ),
        # fraction of the flood-free catch-up rate retained under storm
        # (same run, same topology minus the flooders); 1.0 = free
        "vs_baseline": round(med_clean / med, 3),
        "baseline_control": "same-run flood-free catch-up (rate retained)",
        "config": {
            "slots": slots,
            "validators": 16,
            "spec": "minimal",
            "flooders": flooders,
            "flood_messages_total": flood_sent,
            "clean_blocks_per_sec": round(slots / med_clean, 1),
        },
        "counters": {k: round(after[k] - before[k], 1) for k in after},
        "queue_wait": queue_wait,
        "handler_run": handler_run,
        "spread": spread(flood_times),
        "control_spread": spread(clean_times),
    }


def bench_testnet_soak(jax):
    """Testnet soak: an N-node in-process fleet (real gossipsub/RPC/
    beacon_processor/SyncService per node, duties split across per-node
    VCs) runs healthy epochs, then takes scripted partition-heal cycles.
    Headline: slots finalized per wall-second across the healthy soak
    (per-epoch samples give the spread). The recovery story rides along:
    wall seconds from heal until every node shares one head
    (head_convergence_s) and until finality advances past the heal point
    (recovery_to_finality_s), one sample per cycle. The scenario oracle
    asserts invariants (single head, participation, zero internal
    errors) between phases — a soak that degrades silently fails loudly
    instead of reporting a pretty number."""
    from dataclasses import replace

    from lighthouse_tpu.testing.testnet import (
        ChainHealthOracle,
        Testnet,
        _finalized_epochs,
        _run_to_convergence,
    )
    from lighthouse_tpu.types.chain_spec import minimal_spec
    from lighthouse_tpu.types.eth_spec import MinimalEthSpec as E

    spec = replace(minimal_spec(), altair_fork_epoch=0)
    S = E.SLOTS_PER_EPOCH
    nodes = 3 if SMOKE else 5
    validators = 24 if SMOKE else 40
    soak_epochs = 3 if SMOKE else 5
    cycles = 1 if SMOKE else 2
    # BENCH_TESTNET_API_WORKERS=N boots every full node's Beacon API with
    # N forked serving workers (PR 18) — the A/B lever: a soak at 0 vs a
    # soak at 2 through --compare proves the serving tier doesn't tax the
    # chain's finalization rate
    api_workers = int(os.environ.get("BENCH_TESTNET_API_WORKERS", "0") or 0)
    net = Testnet.create(
        spec,
        E,
        node_count=nodes,
        validator_count=validators,
        seed=2026,
        api_workers=api_workers,
    )
    rates, recoveries, convergences, recovery_slots = [], [], [], []
    try:
        oracle = ChainHealthOracle(net)
        slot = 0
        fin_slots_prev = 0
        for ep in range(1, soak_epochs + 1):
            t0 = time.perf_counter()
            net.run_until_slot(ep * S, start_slot=slot + 1)
            slot = ep * S
            dt = time.perf_counter() - t0
            fin_slots = max(_finalized_epochs(net)) * S
            if fin_slots > fin_slots_prev:
                rates.append((fin_slots - fin_slots_prev) / dt)
                fin_slots_prev = fin_slots
            _partial(epoch=ep, finalized_slots=fin_slots, epoch_s=round(dt, 2))
        oracle.check(
            require_single_head=True,
            min_participation=0.9,
            what="healthy soak",
        )
        for cyc in range(cycles):
            names = [n.name for n in net.nodes]
            net.rng.shuffle(names)
            cut = nodes // 2 + 1
            net.partition(names[:cut], names[cut:])
            end = slot + S
            net.run_until_slot(end, start_slot=slot + 1)
            slot = end
            net.heal()
            rec = _run_to_convergence(net, oracle, start_slot=slot + 1)
            slot += rec["recovery_slots"]
            recoveries.append(rec["recovery_to_finality_s"])
            convergences.append(rec["head_convergence_s"])
            recovery_slots.append(rec["recovery_slots"])
            _partial(
                cycle=cyc + 1,
                of=cycles,
                recovery_to_finality_s=rec["recovery_to_finality_s"],
            )
        oracle.check(require_single_head=True, what="post-cycle fleet")
    finally:
        net.shutdown()

    def spread(samples):
        return {
            "median_s": round(statistics.median(samples), 3),
            "min_s": round(min(samples), 3),
            "max_s": round(max(samples), 3),
            "trials": len(samples),
        }

    from lighthouse_tpu.metrics import REGISTRY

    return {
        "metric": "testnet_soak",
        "value": round(statistics.median(rates), 2),
        "unit": (
            f"slots finalized per wall-second ({nodes}-node fleet, "
            f"healthy soak)"
        ),
        "config": {
            "nodes": nodes,
            "validators": validators,
            "soak_epochs": soak_epochs,
            "partition_heal_cycles": cycles,
            "api_workers": api_workers,
            "seed": net.seed,
            "spec": "minimal",
        },
        # the robustness headline: wall-clock to recover after a heal
        "recovery_to_finality": spread(recoveries),
        "head_convergence": spread(convergences),
        "recovery_slots": recovery_slots,
        "counters": {
            "faults_injected": sum(
                REGISTRY.counter("testnet_fault_injections_total").value(
                    kind=k
                )
                for k in ("partition", "heal")
            ),
            "frames_dropped": REGISTRY.counter(
                "testnet_gossip_frames_dropped_total"
            ).value(),
            "fork_backtracks": REGISTRY.counter(
                "sync_fork_backtracks_total"
            ).value(),
            "oracle_checks_passed": REGISTRY.counter(
                "scenario_invariant_checks_total"
            ).value(result="pass"),
        },
        "spread": {
            "median_rate": round(statistics.median(rates), 2),
            "min_rate": round(min(rates), 2),
            "max_rate": round(max(rates), 2),
            "samples": len(rates),
        },
    }


def bench_checkpoint_boot(jax):
    """Peer checkpoint sync: wall seconds from a bare store to a serving
    chain anchored on a live peer's finalized checkpoint — three HTTP
    round-trips (finality_checkpoints, state SSZ, block SSZ), two local
    tree-root verifications, and the chain boot. The backfill rate rides
    along as a sub-metric: blocks/s filling history backward over the
    RPC while the anchored chain serves forward."""
    from dataclasses import replace

    from lighthouse_tpu.beacon_chain.checkpoint_sync import checkpoint_boot
    from lighthouse_tpu.beacon_chain.harness import (
        HARNESS_GENESIS_TIME,
        BeaconChainHarness,
    )
    from lighthouse_tpu.crypto import bls
    from lighthouse_tpu.http_api import HttpApiServer
    from lighthouse_tpu.network import NetworkService
    from lighthouse_tpu.store import HotColdDB, MemoryStore
    from lighthouse_tpu.types.chain_spec import minimal_spec
    from lighthouse_tpu.types.eth_spec import MinimalEthSpec as E
    from lighthouse_tpu.utils.slot_clock import ManualSlotClock

    bls.set_backend("fake_crypto")
    spec = replace(minimal_spec(), altair_fork_epoch=0)
    S = E.SLOTS_PER_EPOCH
    epochs = 4 if SMOKE else 8
    h = BeaconChainHarness(spec, E, validator_count=16)
    h.extend_chain(epochs * S)
    anchor_slot = None
    srv = HttpApiServer(h.chain).start()
    na = NetworkService(h.chain).start()
    boots, backfill_rates = [], []
    try:
        url = f"http://127.0.0.1:{srv.port}"
        trials = 2 if SMOKE else 3
        for t in range(trials):
            clock = ManualSlotClock(
                genesis_time=HARNESS_GENESIS_TIME,
                seconds_per_slot=spec.seconds_per_slot,
            )
            clock.set_slot(int(h.chain.head_state.slot))
            t0 = time.perf_counter()
            chain = checkpoint_boot(
                url, HotColdDB(MemoryStore()), spec, E, slot_clock=clock
            )
            boots.append(time.perf_counter() - t0)
            anchor_slot = int(chain.anchor_slot)
            nb = NetworkService(chain).start()
            try:
                peer = nb.connect("127.0.0.1", na.port)
                t1 = time.perf_counter()
                stored = nb.sync.backfill(peer)
                dt = time.perf_counter() - t1
                if stored and dt > 0:
                    backfill_rates.append(stored / dt)
            finally:
                nb.stop()
            _partial(trial=t + 1, boot_s=round(boots[-1], 3))
    finally:
        na.stop()
        srv.stop()
    return {
        "metric": "checkpoint_boot_s",
        "value": round(statistics.median(boots), 3),
        "unit": "s to anchored serving chain (fetch+verify+boot)",
        "config": {
            "source_epochs": epochs,
            "anchor_slot": anchor_slot,
            "validators": 16,
            "trials": len(boots),
            "spec": "minimal",
        },
        "sub_metrics": [
            {
                "metric": "checkpoint_backfill_blocks_per_s",
                "value": round(statistics.median(backfill_rates), 1)
                if backfill_rates
                else 0,
                "unit": "blocks/sec backfilled over RPC",
            }
        ],
        "spread": {
            "median_s": round(statistics.median(boots), 3),
            "min_s": round(min(boots), 3),
            "max_s": round(max(boots), 3),
            "trials": len(boots),
        },
    }


def bench_store_soak(jax):
    """Hot-store growth slope with the finality migrator ON vs OFF (the
    `migrator.enabled` A/B seam). With migration every finality advance
    moves finalized blocks cold and prunes hot states, so the hot side's
    byte count flattens after the first finalized epoch; with it off the
    same chain grows the hot side linearly forever. Headline: hot-store
    bytes/epoch over the post-finality tail with migration ON (lower is
    better — the bound the churn-soak oracle enforces); the OFF slope
    and the ON/OFF ratio ride along."""
    from dataclasses import replace

    from lighthouse_tpu.beacon_chain.harness import BeaconChainHarness
    from lighthouse_tpu.crypto import bls
    from lighthouse_tpu.types.chain_spec import minimal_spec
    from lighthouse_tpu.types.eth_spec import MinimalEthSpec as E

    bls.set_backend("fake_crypto")
    spec = replace(minimal_spec(), altair_fork_epoch=0)
    S = E.SLOTS_PER_EPOCH
    epochs = 6 if SMOKE else 10

    def run(migrate):
        h = BeaconChainHarness(spec, E, validator_count=16)
        h.chain.migrator.enabled = migrate
        sizes = []
        for _ep in range(epochs):
            h.extend_chain(S)
            sizes.append(
                h.chain.store.column_stats()["hot"]["total_bytes"]
            )
        return h, sizes

    h_on, on_sizes = run(True)
    _partial(phase="migration_on", hot_bytes=on_sizes[-1])
    h_off, off_sizes = run(False)
    _partial(phase="migration_off", hot_bytes=off_sizes[-1])
    # slope over the post-finality tail only: the first ~3 epochs are
    # pre-finality on both sides and would dilute the contrast
    tail = max(2, epochs // 2)

    def slope(sizes):
        return (sizes[-1] - sizes[-tail]) / (tail - 1)

    slope_on, slope_off = slope(on_sizes), slope(off_sizes)
    # headline is the FINAL hot-store size (positive and stable — a
    # post-finality slope can legitimately go negative, which breaks
    # --compare's relative-regression fraction); slopes ride as details
    return {
        "metric": "store_soak",
        "value": on_sizes[-1],
        "unit": f"hot-store bytes after {epochs} epochs, migration ON",
        "config": {
            "epochs": epochs,
            "tail_epochs": tail,
            "validators": 16,
            "finalized_epoch_on": h_on.finalized_epoch,
            "finalized_epoch_off": h_off.finalized_epoch,
            "split_slot_on": h_on.chain.store.split_slot,
            "spec": "minimal",
        },
        "sub_metrics": [
            {
                "metric": "store_soak_migration_off",
                "value": off_sizes[-1],
                "unit": (
                    f"hot-store bytes after {epochs} epochs, migration "
                    "OFF (control)"
                ),
            }
        ],
        "slopes_bytes_per_epoch": {
            "on_tail": round(slope_on, 1),
            "off_tail": round(slope_off, 1),
        },
        "hot_bytes_per_epoch": {"on": on_sizes, "off": off_sizes},
        "growth_ratio_off_over_on": round(
            off_sizes[-1] / max(on_sizes[-1], 1), 2
        ),
    }


def bench_testnet_churn_soak(jax):
    """Fleet churn soak (the kill/restart regime): every round one node
    of a disk-backed fleet dies with its KV store kept, the fleet runs
    an epoch without it, and it restarts from disk and catches back up —
    the scenario oracle asserts finality never stalls, heads reconverge,
    and the migrator keeps every hot store bounded. Headline: slots
    finalized per wall-second across the whole churn (boot + kill +
    restart + reconvergence included)."""
    from dataclasses import replace

    from lighthouse_tpu.metrics import REGISTRY
    from lighthouse_tpu.testing.testnet import run_churn_soak_scenario
    from lighthouse_tpu.types.chain_spec import minimal_spec
    from lighthouse_tpu.types.eth_spec import MinimalEthSpec as E

    spec = replace(minimal_spec(), altair_fork_epoch=0)
    node_count = 3 if SMOKE else 5
    rounds = 1 if SMOKE else 3
    report = run_churn_soak_scenario(
        spec, E, node_count=node_count, churn_rounds=rounds, seed=2027
    )
    return {
        "metric": "testnet_churn_soak",
        "value": report["finalized_slots_per_wall_s"],
        "unit": (
            f"slots finalized per wall-second ({node_count}-node "
            "disk-backed fleet, kill/restart churn)"
        ),
        "config": {
            "nodes": node_count,
            "churn_rounds": rounds,
            "seed": report["seed"],
            "spec": "minimal",
        },
        "sub_metrics": [
            {
                "metric": "testnet_churn_hot_growth",
                "value": report["hot_store_growth"],
                "unit": "x hot-store growth over churn (migrator bound)",
            }
        ],
        "wall_s": report["wall_s"],
        "finalized_epoch_min": report["finalized_epoch_min"],
        "hot_store_bytes": report["hot_store_bytes"],
        "counters": {
            "kills": REGISTRY.counter(
                "testnet_fault_injections_total"
            ).value(kind="kill"),
            "restarts": REGISTRY.counter(
                "testnet_fault_injections_total"
            ).value(kind="restart"),
            "migrations": REGISTRY.counter(
                "store_migrations_total"
            ).value(),
        },
    }


def bench_fork_choice(jax):
    """Array-program fork choice under a 1M-validator attestation flood:
    per trial, EVERY validator's latest-message vote moves (strictly-newer
    target epoch) across a forked 256-block tree via the batched
    ingestion entry (simulated drained gossip batches of 16384, grouped
    per head root like the network layer's (root, epoch) groups), then
    one `get_head` applies the full 1M-vote delta round. vs_baseline is
    the retained scalar oracle (proto_array_reference) running the SAME
    churn on a 1/16 validator subsample, same run, scaled linearly — the
    oracle's per-validator dict walk is O(votes), so the scaling is
    exact. A riding differential check proves bit-identical head roots
    and node weights at subsample size (batch vs single ingestion,
    proposer boost on/off, equivocations)."""
    import gc

    from lighthouse_tpu.fork_choice.proto_array import ProtoArrayForkChoice
    from lighthouse_tpu.fork_choice.proto_array_reference import (
        ProtoArrayForkChoiceReference,
    )
    from lighthouse_tpu.metrics import REGISTRY

    n_val = 50_000 if SMOKE else 1_000_000
    n_blocks = 64 if SMOKE else 256
    n_heads = min(32, n_blocks // 2)
    batch_size = 16_384

    # 0xAA prefix: an all-zero root is the proto-array's "no vote"
    # sentinel; the anchor must be a realistic non-zero hash
    def root_of(i):
        return b"\xaa" + i.to_bytes(4, "big") + b"\x00" * 27

    tree_rng = random.Random(7)
    edges = [
        (
            i,
            i - 1
            if tree_rng.random() < 0.9
            else tree_rng.randrange(max(1, i - 8), i),
        )
        for i in range(1, n_blocks)
    ]

    def build(cls):
        fc = cls(root_of(0), 0, root_of(0), 0, 0)
        for i, p in edges:
            fc.on_block(
                slot=i, root=root_of(i), parent_root=root_of(p),
                state_root=root_of(i), justified_epoch=0, finalized_epoch=0,
            )
        return fc

    heads = [root_of(i) for i in range(n_blocks - n_heads, n_blocks)]
    rng = np.random.default_rng(11)
    targets = rng.integers(0, n_heads, n_val).astype(np.int64)
    balances = np.full(n_val, 32_000_000_000, dtype=np.uint64)

    fc = build(ProtoArrayForkChoice)
    epoch_counter = [0]

    def run():
        epoch_counter[0] += 1
        epoch = epoch_counter[0]
        for start in range(0, n_val, batch_size):
            chunk_targets = targets[start : start + batch_size]
            base = np.arange(
                start, min(start + batch_size, n_val), dtype=np.int64
            )
            for g in range(n_heads):
                sel = base[chunk_targets == g]
                if sel.size:
                    fc.process_attestation_batch(sel, heads[g], epoch)
        fc.get_head(
            justified_checkpoint_root=root_of(0), justified_epoch=0,
            finalized_epoch=0, justified_state_balances=balances,
        )

    counter = REGISTRY.counter("fork_choice_votes_applied_total")
    before_batch = counter.value(path="batch")
    spans_before = _span_totals(
        ("fork_choice_get_head", "delta_compute", "weight_roll", "best_child")
    )
    run()  # warm-up: first pass allocates the 1M-row columns
    t = _trials(run, n=3, between=gc.collect)
    stages = _span_deltas(
        spans_before,
        _span_totals(
            (
                "fork_choice_get_head",
                "delta_compute",
                "weight_roll",
                "best_child",
            )
        ),
    )
    votes_applied = counter.value(path="batch") - before_batch

    # scalar oracle on a 1/16 subsample, same churn, same run
    sub = n_val // 16
    ref = build(ProtoArrayForkChoiceReference)
    bal_list = [32_000_000_000] * sub
    ctrl_times = []
    for trial in range(2):
        epoch = trial + 1
        t0 = time.perf_counter()
        for v in range(sub):
            ref.process_attestation(v, heads[int(targets[v])], epoch)
        ref.get_head(
            justified_checkpoint_root=root_of(0), justified_epoch=0,
            finalized_epoch=0, justified_state_balances=bal_list,
        )
        ctrl_times.append(time.perf_counter() - t0)
        _partial(control_trial=trial + 1, of=2, s=round(ctrl_times[-1], 4))
    ctrl_scaled = statistics.median(ctrl_times) * 16

    # riding differential: columnar vs oracle, identical subsample votes
    dc = build(ProtoArrayForkChoice)
    dr = build(ProtoArrayForkChoiceReference)
    diff_bal = np.full(sub, 32_000_000_000, dtype=np.uint64)
    for round_i, (boost, eq) in enumerate(
        ((b"\x00" * 32, set()), (heads[0], set()), (b"\x00" * 32, {1, 5}))
    ):
        epoch = round_i + 1
        idx = np.arange(sub, dtype=np.int64)
        for g in range(n_heads):
            sel = idx[targets[:sub] == g]
            if sel.size:
                dc.process_attestation_batch(sel, heads[g], epoch)
        for v in range(sub):
            dr.process_attestation(v, heads[int(targets[v])], epoch)
        kw = dict(
            justified_checkpoint_root=root_of(0), justified_epoch=0,
            finalized_epoch=0,
            proposer_boost_root=boost,
            proposer_boost_amount=1_000_000_000_000 if boost != b"\x00" * 32 else 0,
            equivocating_indices=eq,
        )
        h1 = dc.get_head(justified_state_balances=diff_bal, **kw)
        h2 = dr.get_head(justified_state_balances=list(diff_bal.tolist()), **kw)
        assert h1 == h2, "columnar vs scalar head mismatch"
        w1 = dc.proto_array._weights[: dc.proto_array._n].tolist()
        w2 = [n.weight for n in dr.proto_array.nodes]
        assert w1 == w2, "columnar vs scalar weight mismatch"

    return {
        "metric": "fork_choice_get_head_ms",
        "value": round(t["median_s"] * 1000, 2),
        "unit": (
            f"ms/round ({n_val} votes moved + get_head, "
            f"{n_blocks}-block forked tree)"
        ),
        "vs_baseline": round(ctrl_scaled / t["median_s"], 2),
        "baseline_control": (
            "retained scalar oracle (proto_array_reference) on a 1/16 "
            "validator subsample, same churn, same run, scaled x16"
        ),
        "config": {
            "validators": n_val,
            "blocks": n_blocks,
            "vote_groups": n_heads,
            "batch_size": batch_size,
            "votes_applied_batch": int(votes_applied),
            "oracle_scaled_ms": round(ctrl_scaled * 1000, 1),
            "differential_check": "passed",
        },
        "stages": stages,
        "spread": t,
        "control_spread": {
            "median_s": statistics.median(ctrl_times),
            "min_s": min(ctrl_times),
            "max_s": max(ctrl_times),
            "trials": len(ctrl_times),
        },
    }


def bench_op_pool(jax):
    """Columnar op-pool block packing under a 500k-attestation pool:
    `get_attestations_for_block` as a flat array program (pre-grouped
    buckets, resident masks, one gains vector + np.argmax per round) vs
    the retained rescan reference — which re-hashes every candidate's
    data root and recomputes the full gains list per round — on a 1/16
    bucket subsample, same run, scaled linearly (both walks are
    O(candidates x rounds)). A riding differential check proves the flat
    pack and the rescan pack choose the IDENTICAL attestation list on
    the same subsample pool."""
    import gc

    from lighthouse_tpu.beacon_chain.op_pool import OperationPool
    from lighthouse_tpu.types.containers import build_types
    from lighthouse_tpu.types.eth_spec import MinimalEthSpec as E

    n_pool = 20_000 if SMOKE else 500_000
    per_bucket = OperationPool.MAX_AGGREGATES_PER_DATA  # 16
    n_buckets = n_pool // per_bucket
    width = 128  # mainnet-shaped committee

    state, spec, _ = _build_epoch_state(64, resident=True)
    state.slot = int(state.slot) + 1
    t_types = build_types(E)
    current_epoch = int(state.slot) // E.SLOTS_PER_EPOCH
    source = state.current_justified_checkpoint
    target = t_types.Checkpoint(epoch=current_epoch, root=b"\x22" * 32)

    pool = OperationPool(spec, E)
    rng = np.random.default_rng(3)
    build_t0 = time.perf_counter()
    slots = [int(state.slot) - 1 - (i % 4) for i in range(n_buckets)]
    for b in range(n_buckets):
        data = t_types.AttestationData(
            slot=slots[b],
            index=b,
            beacon_block_root=b"\x11" * 32,
            source=source,
            target=target,
        )
        patterns = rng.random((per_bucket, width)) < 0.25
        patterns[:, 0] = True  # overlap on bit 0: no merges, no BLS
        for j in range(per_bucket):
            pool._add_unmerged(
                t_types.Attestation(
                    aggregation_bits=patterns[j].tolist(),
                    data=data,
                    signature=b"\x00" * 96,
                )
            )
        if b and b % 8192 == 0:
            _partial(pool_build_buckets=b, of=n_buckets)
    build_s = time.perf_counter() - build_t0
    assert pool.num_attestations() == n_pool

    def run():
        packed = pool.get_attestations_for_block(state)
        assert 0 < len(packed) <= E.MAX_ATTESTATIONS

    run()  # warm-up (numpy allocators)
    t = _trials(run, n=3, between=gc.collect)

    # rescan reference on a 1/16 bucket subsample, same run
    sub = OperationPool(spec, E)
    sub._attestations = {
        k: v
        for i, (k, v) in enumerate(pool._attestations.items())
        if i % 16 == 0
    }
    ctrl_times = []
    for trial in range(2):
        t0 = time.perf_counter()
        ref_packed = sub.get_attestations_for_block_reference(state)
        ctrl_times.append(time.perf_counter() - t0)
        _partial(control_trial=trial + 1, of=2, s=round(ctrl_times[-1], 4))
    ctrl_scaled = statistics.median(ctrl_times) * 16

    # riding differential: flat vs rescan on the SAME subsample pool
    assert sub.get_attestations_for_block(state) == ref_packed, (
        "flat vs rescan pack mismatch"
    )

    return {
        "metric": "op_pool_pack_ms",
        "value": round(t["median_s"] * 1000, 2),
        "unit": f"ms/pack ({n_pool}-attestation pool, {n_buckets} buckets)",
        "vs_baseline": round(ctrl_scaled / t["median_s"], 2),
        "baseline_control": (
            "retained rescan walk (get_attestations_for_block_reference) "
            "on a 1/16 bucket subsample, same run, scaled x16"
        ),
        "config": {
            "pool_attestations": n_pool,
            "buckets": n_buckets,
            "aggregates_per_bucket": per_bucket,
            "bits_width": width,
            "max_attestations": E.MAX_ATTESTATIONS,
            "pool_build_s": round(build_s, 2),
            "rescan_scaled_ms": round(ctrl_scaled * 1000, 1),
            "differential_check": "passed",
        },
        "spread": t,
        "control_spread": {
            "median_s": statistics.median(ctrl_times),
            "min_s": min(ctrl_times),
            "max_s": max(ctrl_times),
            "trials": len(ctrl_times),
        },
    }


def bench_slasher_ingest(jax):
    """Columnar slasher ingesting ONE EPOCH's full mainnet-shape
    attestation flood at 1M validators (2048 aggregates, ~490-member
    committees, every validator attesting once): per trial, a pre-warmed
    engine (one prior epoch recorded, so min/max spans and record columns
    are populated) consumes the flood queue in one `process_queued` cycle
    — batched data-root hashing, grouped span gather/compare, bulk
    span writebacks. A seeded-slashing RECALL check rides every trial: a
    planted double vote plus surrounds in BOTH directions must all be
    found, exactly, with zero false emissions. vs_baseline is the
    retained scalar engine (slasher/reference.py) on a 1/16 validator
    subsample — same committee size, 1/16 of the committees, same warm
    epoch — same run, scaled linearly (the scalar walk is O(attesting
    indices)). A riding differential check proves columnar ≡ scalar
    emissions on the subsample flood incl. the planted offenders."""
    import gc

    from lighthouse_tpu.metrics import REGISTRY
    from lighthouse_tpu.slasher.columnar import ColumnarSlasher
    from lighthouse_tpu.slasher.reference import ReferenceSlasher
    from lighthouse_tpu.types.containers import build_types
    from lighthouse_tpu.types.eth_spec import MainnetEthSpec as E

    T = build_types(E)
    n_val = 65_536 if SMOKE else 1_000_000
    n_comm = 128 if SMOKE else 2048  # 64 committees x 32 slots
    warm_epoch, flood_epoch = 10, 11
    # planted offenders: victims of a double vote and both surround
    # directions, detected DURING the timed flood cycle
    v_double, v_surrounded, v_surrounder = 100, 200, 300

    def make_flood(source, target, n_validators, committees, seed):
        rng = np.random.default_rng(seed)
        chunks = np.array_split(rng.permutation(n_validators), committees)
        cp = T.Checkpoint(epoch=source, root=b"\x01" * 32)
        ct = T.Checkpoint(epoch=target, root=b"\x02" * 32)
        return [
            T.IndexedAttestation(
                attesting_indices=np.sort(ch).tolist(),
                data=T.AttestationData(
                    slot=target * E.SLOTS_PER_EPOCH + (i % E.SLOTS_PER_EPOCH),
                    index=i // E.SLOTS_PER_EPOCH,
                    beacon_block_root=b"\x03" * 32,
                    source=cp,
                    target=ct,
                ),
                signature=b"\x00" * 96,
            )
            for i, ch in enumerate(chunks)
        ]

    def single(vi, source, target, root):
        return T.IndexedAttestation(
            attesting_indices=[vi],
            data=T.AttestationData(
                slot=target * E.SLOTS_PER_EPOCH,
                index=0,
                beacon_block_root=root,
                source=T.Checkpoint(epoch=source, root=b"\x01" * 32),
                target=T.Checkpoint(epoch=target, root=b"\x01" * 32),
            ),
            signature=b"\x00" * 96,
        )

    def planted_warm():
        # v_surrounded's wide old record will surround its own honest
        # flood vote; v_surrounder's narrow record gets surrounded by a
        # planted attacker vote in the flood
        return [
            single(v_surrounded, 8, 13, b"\xaa" * 32),
            single(v_surrounder, 11, 12, b"\xbb" * 32),
        ]

    def planted_flood():
        return [
            single(v_double, warm_epoch, flood_epoch, b"\xcc" * 32),
            single(v_surrounder, 10, 13, b"\xdd" * 32),
        ]

    build_t0 = time.perf_counter()
    warm = make_flood(warm_epoch - 1, warm_epoch, n_val, n_comm, seed=1)
    flood = make_flood(warm_epoch, flood_epoch, n_val, n_comm, seed=2)
    build_s = time.perf_counter() - build_t0
    n_atts = len(flood) + len(planted_flood())

    trials = 3
    _partial(stage="warming", engines=trials)
    engines = []
    for _ in range(trials):
        s = ColumnarSlasher(E)
        for a in warm + planted_warm():
            s.accept_attestation(a)
        s.process_queued(warm_epoch)  # untimed: prior-epoch span state
        s.drain_slashings()  # discard warm-cycle findings (the planted
        # wide record itself surrounds its victim's honest warm vote);
        # the timed cycle must find exactly the three planted offenders
        engines.append(s)

    scans = REGISTRY.counter("slasher_exact_scans_total")
    spans_before = _span_totals(
        ("slasher_process", "span_gather", "span_compare", "span_update", "persist")
    )
    scans_before = scans.value()
    recall = {}

    def run():
        s = engines.pop()
        for a in flood + planted_flood():
            s.accept_attestation(a)
        out = s.process_queued(flood_epoch)
        # riding recall assertion: all three planted offenders, nothing else
        assert out["attester_slashings"] == 3, out
        atts, _ = s.drain_slashings()
        offenders = {
            int(
                (
                    set(a.attestation_1.attesting_indices)
                    & set(a.attestation_2.attesting_indices)
                ).pop()
            )
            for a in atts
        }
        assert offenders == {v_double, v_surrounded, v_surrounder}, offenders
        recall["planted"] = 3
        recall["found"] = len(atts)

    t = _trials(run, n=trials, between=gc.collect)
    stages = _span_deltas(
        spans_before,
        _span_totals(
            (
                "slasher_process",
                "span_gather",
                "span_compare",
                "span_update",
                "persist",
            )
        ),
    )
    exact_scans = scans.value() - scans_before

    # scalar reference on a 1/16 subsample: same committee size, 1/16 of
    # the committees (both per-item and per-index costs scale linearly)
    sub_val, sub_comm = n_val // 16, n_comm // 16
    sub_warm = make_flood(warm_epoch - 1, warm_epoch, sub_val, sub_comm, seed=1)
    sub_flood = make_flood(warm_epoch, flood_epoch, sub_val, sub_comm, seed=2)
    ctrl_times = []
    for trial in range(2):
        r = ReferenceSlasher(E)
        for a in sub_warm + planted_warm():
            r.accept_attestation(a)
        r.process_queued(warm_epoch)
        r.drain_slashings()
        for a in sub_flood + planted_flood():
            r.accept_attestation(a)
        t0 = time.perf_counter()
        out = r.process_queued(flood_epoch)
        ctrl_times.append(time.perf_counter() - t0)
        assert out["attester_slashings"] == 3, out
        _partial(control_trial=trial + 1, of=2, s=round(ctrl_times[-1], 4))
    ctrl_scaled = statistics.median(ctrl_times) * 16

    # riding differential: columnar vs scalar on the SAME subsample flood
    dc = ColumnarSlasher(E)
    dr = ReferenceSlasher(E)
    for engine in (dc, dr):
        for a in sub_warm + planted_warm():
            engine.accept_attestation(a)
        engine.process_queued(warm_epoch)
        for a in sub_flood + planted_flood():
            engine.accept_attestation(a)
        engine.process_queued(flood_epoch)
        # fingerprint covers BOTH cycles' emissions (warm incl. the
        # planted wide record's own surround finding)
    fp_c = [
        (a.attestation_1.serialize(), a.attestation_2.serialize())
        for a in dc.drain_slashings()[0]
    ]
    fp_r = [
        (a.attestation_1.serialize(), a.attestation_2.serialize())
        for a in dr.drain_slashings()[0]
    ]
    assert fp_c == fp_r, "columnar vs scalar emission mismatch"

    atts_per_sec = n_atts / t["median_s"]
    ctrl_atts_per_sec = n_atts / ctrl_scaled
    return {
        "metric": "slasher_ingest",
        "value": round(atts_per_sec, 1),
        "unit": (
            f"atts/sec (one epoch's {n_atts}-aggregate mainnet flood at "
            f"{n_val} validators, seeded-slashing recall riding)"
        ),
        "vs_baseline": round(atts_per_sec / ctrl_atts_per_sec, 2),
        "baseline_control": (
            "retained scalar engine (slasher/reference.py) on a 1/16 "
            "validator subsample (1/16 of the committees, same committee "
            "size), same run, scaled x16"
        ),
        "config": {
            "validators": n_val,
            "aggregates": n_atts,
            "committee_size": n_val // n_comm,
            "cycle_ms": round(t["median_s"] * 1000, 1),
            "validator_attestations_per_sec": round(n_val / t["median_s"]),
            "scalar_scaled_ms": round(ctrl_scaled * 1000, 1),
            "flood_build_s": round(build_s, 2),
            "exact_scans": int(exact_scans),
            "recall": recall,
            "differential_check": "passed",
        },
        "stages": stages,
        "spread": t,
        "control_spread": {
            "median_s": statistics.median(ctrl_times),
            "min_s": min(ctrl_times),
            "max_s": max(ctrl_times),
            "trials": len(ctrl_times),
        },
    }


def bench_api_throughput(jax):
    """The Beacon API serving tier at the 1M-validator state: the
    full-table `/states/head/validators` response assembled zero-copy
    from the resident columns (PR 14) under three regimes — COLD (cache
    cleared per request: the assembly cost), HOT (head-keyed response
    cache: the steady dashboard-fleet case), and a PAGINATED SCAN
    (1000-row pages sweeping the whole table cold: slice-gather cost).
    vs_baseline is the retained per-object oracle
    (`state_validators_reference`) rendering the SAME full table in the
    same run, and the cold body must be BYTE-IDENTICAL to the oracle's
    compact JSON — the riding differential."""
    import gc

    from lighthouse_tpu.beacon_chain.events import ServerSentEventHandler
    from lighthouse_tpu.http_api import BeaconApi
    from lighthouse_tpu.metrics import REGISTRY
    from lighthouse_tpu.types.eth_spec import MinimalEthSpec as E

    n = 20_000 if SMOKE else 1_000_000
    page = 500 if SMOKE else 1000
    # the full-table body (~460 MB at 1M) must fit the cache for the hot
    # regime to exercise it
    os.environ["LIGHTHOUSE_TPU_API_CACHE_BYTES"] = str(2 << 30)
    state, _vs = _build_1m_state(n)
    # diversify ~n/256 rows so the status vectorization and filters see
    # every spec family, not one constant
    rng = random.Random(3)
    far = 2**64 - 1
    for _ in range(max(64, n // 256)):
        v = state.validators.mutate(rng.randrange(n))
        kind = rng.randrange(4)
        if kind == 0:
            v.exit_epoch, v.withdrawable_epoch = 0, 9  # exited
        elif kind == 1:
            v.slashed, v.exit_epoch, v.withdrawable_epoch = True, 3, 9
        elif kind == 2:
            v.activation_epoch, v.activation_eligibility_epoch = far, far
        else:
            v.exit_epoch, v.withdrawable_epoch = 0, 0  # withdrawal
    _partial(fixture="diversified")

    class _Chain:
        pass

    chain = _Chain()
    chain.head_state = state
    chain.head_root = b"\xab" * 32
    chain._states = {chain.head_root: state}
    chain._blocks_by_root = {}
    chain.genesis_block_root = chain.head_root
    chain.genesis_validators_root = bytes(state.genesis_validators_root)
    chain.event_handler = ServerSentEventHandler()
    chain.E = E
    chain.store = None
    api = BeaconApi(chain)

    spans_before = _span_totals(("cache_lookup", "assemble", "serialize"))
    assembled = REGISTRY.counter("api_columnar_assembly_total")
    assembled_before = assembled.value(route="validators")
    hits = REGISTRY.counter("api_cache_hits_total")
    hits_before = hits.value(route="validators")

    # -- cold: full-table assembly, RESPONSE cache cleared per request ---
    # (one untimed warm-up first: the resident assembly caches — index
    # pieces, per-column hexlify pieces — build once per column
    # residency, exactly like a serving node's steady state; "cold"
    # means the response cache missed, not that the process is fresh)
    body_box = {}

    def cold():
        api.response_cache.clear()
        body_box["body"], _ = api.serve_state_validators("head")

    t0 = time.perf_counter()
    cold()
    _partial(warmup_s=round(time.perf_counter() - t0, 3))
    gc.collect()
    t_cold = _trials(cold, n=5, label="cold_trial", between=gc.collect)
    body = body_box["body"]

    # -- per-object oracle on the SAME full table, same run --------------
    ref_box = {}

    def oracle():
        ref_box["ref"] = json.dumps(
            api.state_validators_reference(state), separators=(",", ":")
        ).encode()

    t_oracle = _trials(oracle, n=2, label="oracle_trial", between=gc.collect)
    assert body == ref_box["ref"], (
        "columnar full-table body differs from the per-object oracle"
    )
    del ref_box
    gc.collect()

    # -- hot: the head-keyed response cache serves the cached body -------
    api.serve_state_validators("head")  # prime

    hot_batch = 50 if SMOKE else 200

    def hot():
        for _ in range(hot_batch):
            api.serve_state_validators("head")

    t_hot = _trials(hot, n=3, label="hot_trial")
    hot_rps = hot_batch / t_hot["median_s"]
    lat = []
    for _ in range(500):
        t0 = time.perf_counter()
        api.serve_state_validators("head")
        lat.append(time.perf_counter() - t0)
    lat.sort()
    hot_p50_us = lat[len(lat) // 2] * 1e6
    hot_p99_us = lat[int(len(lat) * 0.99)] * 1e6

    # -- paginated scan: 1000-row pages sweep the whole table cold -------
    api.response_cache.clear()
    page_lat = []
    t0 = time.perf_counter()
    for off in range(0, n, page):
        p0 = time.perf_counter()
        api.serve_state_validators(
            "head", {"limit": str(page), "offset": str(off)}
        )
        page_lat.append(time.perf_counter() - p0)
    paginated_s = time.perf_counter() - t0
    page_lat.sort()
    pages = len(page_lat)
    _partial(paginated_pages=pages, s=round(paginated_s, 3))

    # the zero-copy floor: the SSZ balances body is one interleave
    api.response_cache.clear()
    t0 = time.perf_counter()
    ssz_body, _ = api.serve_state_validator_balances("head", ssz=True)
    ssz_ms = (time.perf_counter() - t0) * 1000
    assert len(ssz_body) == n * 16

    # -- multi-process serving workers (PR 18): the same columns behind
    # the pre-fork accept tier, measured through real HTTP ---------------
    import hashlib
    import threading
    import urllib.request

    from lighthouse_tpu.http_api import HttpApiServer

    cores = os.cpu_count() or 1
    client_threads = 4 if SMOKE else 8
    load_s = 2.0 if SMOKE else 4.0
    page_offsets = (0, (n // 2 // page) * page, ((n - page) // page) * page)
    table_path = "/eth/v1/beacon/states/head/validators"

    def _digest_get(port, path):
        """(headers, sha256, size) — streamed, so full-table bodies never
        pile up in client memory. `http.client` returns a silent short
        read when the peer closes mid-body under sized reads (no
        IncompleteRead), so the Content-Length is re-checked: a transfer
        truncated by a retiring worker's proxy leg must surface as a
        retryable fault, not digest as a complete body."""
        req = urllib.request.Request(f"http://127.0.0.1:{port}{path}")
        with urllib.request.urlopen(req, timeout=120) as r:
            hasher = hashlib.sha256()
            size = 0
            while True:
                chunk = r.read(1 << 20)
                if not chunk:
                    break
                hasher.update(chunk)
                size += len(chunk)
            cl = r.headers.get("Content-Length")
            if cl is not None and size != int(cl):
                raise IOError(
                    f"truncated transfer: {size} of {cl} bytes from {path}"
                )
            return dict(r.headers), hasher.hexdigest(), size

    def _load(port, seconds):
        """Concurrent paginated-page GETs (the small-body dashboard
        workload — full-table transfers would measure loopback bandwidth,
        not the serving tier) for `seconds`; returns (req/sec, errors)."""
        stop_at = time.perf_counter() + seconds
        counts = [0] * client_threads
        errors = [0] * client_threads

        def run(i):
            k = i
            while time.perf_counter() < stop_at:
                off = page_offsets[k % len(page_offsets)]
                k += 1
                try:
                    with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}{table_path}"
                        f"?limit={page}&offset={off}",
                        timeout=30,
                    ) as r:
                        r.read()
                    counts[i] += 1
                except Exception:  # noqa: BLE001 — tallied, asserted zero
                    errors[i] += 1

        threads = [
            threading.Thread(target=run, args=(i,))
            for i in range(client_threads)
        ]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        return sum(counts) / wall, sum(errors)

    def _burst_digests(port, names, attempts=10):
        """Bursts of concurrent full-table GETs until every server id in
        `names` has answered; {server_id: (digest, size)}. Concurrency is
        what spreads the accepts — sequential requests can all land on
        one replica. Per-request faults (a retiring worker's socket
        handover mid-rotation) are retried across attempts; only a final
        round that still faults, or never covering `names`, fails."""
        seen = {}
        faults = []
        for _ in range(attempts):
            results, faults = [], []

            def one():
                try:
                    hd, dg, size = _digest_get(port, table_path)
                    results.append((hd["X-Api-Served-By"], dg, size))
                except Exception as e:  # noqa: BLE001 — asserted below
                    faults.append(e)

            burst = [
                threading.Thread(target=one)
                for _ in range(min(client_threads, 4))
            ]
            for t in burst:
                t.start()
            for t in burst:
                t.join()
            for who, dg, size in results:
                seen[who] = (dg, size)
            if not faults and names <= set(seen):
                return seen
        assert not faults, f"full-table burst kept failing: {faults[0]!r}"
        raise AssertionError(
            f"server ids seen {sorted(seen)} never covered {sorted(names)}"
        )

    def _assert_identical(seen, parent_digest, parent_size, when):
        bad = {
            who: (dg[:16], size)
            for who, (dg, size) in sorted(seen.items())
            if dg != parent_digest
        }
        assert not bad, (
            f"{when} replica body diverged from the parent "
            f"(parent {parent_digest[:16]}/{parent_size}B): {bad}"
        )

    respawns = REGISTRY.counter("api_worker_respawns_total")
    w_axis = {}
    for W in (1, 4):
        srv = HttpApiServer(chain, workers=W)
        # prime the response cache BEFORE start(): the fork inherits the
        # hot full-table body by CoW — every replica is born warm
        srv.api.serve_state_validators("head")
        srv.start()
        try:
            ready_by = time.monotonic() + 20
            while True:
                try:
                    _digest_get(srv.port, "/eth/v1/node/health")
                    break
                except Exception:  # noqa: BLE001 — replicas still booting
                    if time.monotonic() > ready_by:
                        raise
                    time.sleep(0.1)
            rps, errs = _load(srv.port, load_s)
            assert errs == 0, f"workers={W}: {errs} failed requests"
            w_axis[W] = round(rps, 1)
            _partial(workers=W, paginated_rps=w_axis[W])
            if W == 4:
                # full-table bodies byte-identical from EVERY replica
                # (compared by streamed digest against the parent's serve)
                _, parent_digest, parent_size = _digest_get(
                    srv.parent_port, table_path
                )
                assert parent_size == len(body)
                names = {w["name"] for w in srv._pool.worker_info()}
                assert len(names) == 4
                seen = _burst_digests(srv.port, names)
                _assert_identical(seen, parent_digest, parent_size, "steady")
                # …and across a head-change invalidation: stale replicas
                # forward to the parent, the supervisor rotates them onto
                # a fresh CoW snapshot, and the bytes never waver.
                # Rotation is DEMAND-driven (ApiWorkerPool rotates only
                # after a stale forward reaches the parent), so keep reads
                # flowing while waiting — a single probe can race the
                # replicas' generation-event pipes and leave the pool
                # without any demand signal, stalling rotation forever
                r_before = respawns.value(reason="head_refresh")
                chain.event_handler.register_head(
                    chain.head_root, int(state.slot), b"\x11" * 32
                )
                rotate_by = time.monotonic() + 30
                while respawns.value(reason="head_refresh") == r_before:
                    _, dg, size = _digest_get(srv.port, table_path)
                    assert dg == parent_digest, (
                        f"mid-rotation body diverged: {dg[:16]}/{size}B vs "
                        f"parent {parent_digest[:16]}/{parent_size}B"
                    )
                    assert time.monotonic() < rotate_by, (
                        "head event never rotated the replicas"
                    )
                    time.sleep(0.1)
                seen = _burst_digests(
                    srv.port, {w["name"] for w in srv._pool.worker_info()}
                )
                _assert_identical(
                    seen, parent_digest, parent_size, "post-rotation"
                )
                _partial(workers=4, identity="passed", rotations=int(
                    respawns.value(reason="head_refresh") - r_before
                ))
        finally:
            srv.stop()
    speedup = round(w_axis[4] / w_axis[1], 2) if w_axis[1] else 0.0
    if cores >= 4:
        assert speedup >= 1.8, (
            f"workers=4 speedup {speedup}x < 1.8x on {cores} cores"
        )
    else:
        # a 1-core box cannot show parallel speedup; the floor asserts
        # the tier doesn't grossly TAX throughput. Four processes
        # time-slicing one core pay real scheduler overhead (~0.75-0.85x
        # observed), hence 0.7, not 1.0
        floor = float(os.environ.get("BENCH_API_WORKERS_MIN_RATIO", "0.7"))
        assert speedup >= floor, (
            f"workers=4 at {speedup}x of workers=1 on {cores} core(s) — "
            f"below the no-regression floor {floor}"
        )

    stages = _span_deltas(
        spans_before, _span_totals(("cache_lookup", "assemble", "serialize"))
    )
    return {
        "metric": "api_throughput",
        "value": round(hot_rps, 1),
        "unit": (
            f"req/sec (hot-cache full-table validators at {n} validators)"
        ),
        "vs_baseline": round(t_oracle["median_s"] / t_cold["median_s"], 2),
        "baseline_control": (
            "retained per-object oracle (state_validators_reference) on "
            "the SAME full table, same run; cold columnar body asserted "
            "byte-identical to it"
        ),
        "config": {
            "validators": n,
            "body_bytes": len(body),
            "cold_full_table_ms": round(t_cold["median_s"] * 1000, 1),
            "oracle_full_table_ms": round(t_oracle["median_s"] * 1000, 1),
            "hot_cache": {
                "rps": round(hot_rps, 1),
                "p50_us": round(hot_p50_us, 1),
                "p99_us": round(hot_p99_us, 1),
            },
            "paginated_scan": {
                "pages": pages,
                "page_rows": page,
                "rps": round(pages / paginated_s, 1),
                "p50_ms": round(page_lat[pages // 2] * 1000, 2),
                "p99_ms": round(page_lat[int(pages * 0.99)] * 1000, 2),
            },
            "balances_ssz_full_table_ms": round(ssz_ms, 2),
            "columnar_requests": int(
                assembled.value(route="validators") - assembled_before
            ),
            "cache_hits": int(hits.value(route="validators") - hits_before),
            "differential_check": "passed",
            "workers_axis": {
                "cores": cores,
                "client_threads": client_threads,
                "workers1_rps": w_axis[1],
                "workers4_rps": w_axis[4],
                "speedup": speedup,
                "full_table_identity": "passed",
                "head_refresh_identity": "passed",
            },
        },
        "sub_metrics": [
            {
                "metric": "api_throughput_workers1",
                "value": w_axis[1],
                "unit": (
                    f"req/sec (paginated pages via HTTP, workers=1, "
                    f"{cores} cores)"
                ),
            },
            {
                "metric": "api_throughput_workers4",
                "value": w_axis[4],
                "unit": (
                    f"req/sec (paginated pages via HTTP, workers=4, "
                    f"{cores} cores)"
                ),
            },
        ],
        "stages": stages,
        "spread": t_cold,
        "control_spread": t_oracle,
    }


def bench_sse_fanout(jax):
    """The SSE broadcast fan-out tier (PR 18) at dashboard-fleet scale:
    one handler, 10k subscribers, head events published at a paced
    cadence (a burst would just measure queue backlog). Each event is
    serialized ONCE and the shared frame lands on every matching
    subscriber queue via the dedicated broadcast thread; sentinel drainer
    threads measure publish→drain lag end to end. A separate phase proves
    slow-consumer eviction is drop-counted, never blocking the publisher.
    vs_baseline is the naive tier — re-serializing per subscriber —
    measured over the same subscriber population, same run."""
    import gc
    import threading

    from lighthouse_tpu.beacon_chain import events as ev_mod
    from lighthouse_tpu.beacon_chain.events import (
        EventSubscription,
        ServerSentEventHandler,
        sse_frame,
    )
    from lighthouse_tpu.metrics import REGISTRY

    dropped = REGISTRY.counter("sse_dropped_total")
    delivered = REGISTRY.counter("sse_events_delivered_total")
    serialized = REGISTRY.counter("sse_events_serialized_total")
    drop_reasons = ("slow_consumer", "evicted", "publish_overflow")

    subs_small = 200 if SMOKE else 1000
    subs_big = 1000 if SMOKE else 10_000
    events_small = 50 if SMOKE else 200
    events_big = 20 if SMOKE else 40
    sentinels = 8
    pace_small_s = 0.002
    pace_big_s = 0.05
    p99_cap_ms = float(os.environ.get("BENCH_SSE_P99_MS", "250"))

    def publish(h, count, pace_s, start=0):
        for i in range(count):
            h.register_head(bytes([i % 256]) * 32, start + i, b"\x01" * 32)
            if pace_s:
                time.sleep(pace_s)

    h = ServerSentEventHandler()

    # -- phase 1: 1k subscribers, ZERO drops at paced head cadence -------
    subs = [h.subscribe(["head"]) for _ in range(subs_small)]
    drops_before = {r: dropped.value(reason=r) for r in drop_reasons}
    ser_before = serialized.value()
    publish(h, events_small, pace_small_s)
    assert h.flush(60.0)
    for r, v in drops_before.items():
        assert dropped.value(reason=r) == v, f"phase-1 drops (reason={r})"
    # serialize-once: one frame per EVENT, not per (event, subscriber)
    assert serialized.value() - ser_before == events_small
    # queue cap (256) above the event count: nothing displaced anywhere
    for s in (subs[0], subs[len(subs) // 2], subs[-1]):
        assert s._q.qsize() == events_small
    for s in subs:
        h.unsubscribe(s)
    _partial(phase="zero_drops", subscribers=subs_small, events=events_small)
    gc.collect()

    # -- phase 2: 10k subscribers, sentinel-measured publish→drain lag ---
    subs = [h.subscribe(["head"]) for _ in range(subs_big - sentinels)]
    sentinel_subs = [h.subscribe(["head"]) for _ in range(sentinels)]
    lags, lag_lock = [], threading.Lock()
    stop = threading.Event()

    def drain(sub):
        local = []
        while True:
            rec = sub.poll_record(timeout=0.05)
            if rec is not None:
                local.append(time.monotonic() - rec[2])
            elif stop.is_set():
                break
        with lag_lock:
            lags.extend(local)

    drainers = [
        threading.Thread(target=drain, args=(s,)) for s in sentinel_subs
    ]
    for t in drainers:
        t.start()
    del_before = delivered.value()
    drops_before = {r: dropped.value(reason=r) for r in drop_reasons}
    t0 = time.perf_counter()
    publish(h, events_big, pace_big_s, start=1000)
    assert h.flush(120.0)
    fan_wall = time.perf_counter() - t0
    stop.set()
    for t in drainers:
        t.join(30.0)
    deliveries = delivered.value() - del_before
    assert deliveries == events_big * subs_big
    rate = deliveries / fan_wall
    for r, v in drops_before.items():
        assert dropped.value(reason=r) == v, f"phase-2 drops (reason={r})"
    assert len(lags) == events_big * sentinels
    lags.sort()
    lag_p50_ms = lags[len(lags) // 2] * 1000
    lag_p99_ms = lags[int(len(lags) * 0.99)] * 1000
    assert lag_p99_ms < p99_cap_ms, (
        f"p99 publish→drain lag {lag_p99_ms:.1f} ms ≥ {p99_cap_ms} ms"
    )
    _partial(
        phase="fanout",
        subscribers=subs_big,
        deliveries_per_sec=round(rate, 1),
        p99_ms=round(lag_p99_ms, 2),
    )

    # -- control: the naive tier serializes per SUBSCRIBER ---------------
    # (same population size, same _offer machinery, same run; the only
    # difference is where sse_frame runs — the shared-frame economics)
    ctrl = [EventSubscription(("head",)) for _ in range(subs_big)]
    ev = {
        "topic": "head",
        "data": {
            "slot": "1",
            "block": "0x" + "ab" * 32,
            "state": "0x" + "cd" * 32,
        },
    }
    rounds = 3
    t0 = time.perf_counter()
    for _ in range(rounds):
        for s in ctrl:
            s._offer((ev, sse_frame(ev).encode(), t0))
    naive_s = (time.perf_counter() - t0) / rounds
    t0 = time.perf_counter()
    for _ in range(rounds):
        frame = sse_frame(ev).encode()
        for s in ctrl:
            s._offer((ev, frame, t0))
    shared_s = (time.perf_counter() - t0) / rounds
    vs_baseline = round(naive_s / shared_s, 2) if shared_s else 0.0
    del ctrl
    for s in subs + sentinel_subs:
        h.unsubscribe(s)
    gc.collect()

    # -- phase 3: a wedged consumer is evicted, never blocks -------------
    stuck = h.subscribe(["head"])
    evict_before = dropped.value(reason="evicted")
    slow_before = dropped.value(reason="slow_consumer")
    t0 = time.perf_counter()
    publish(h, ev_mod._QUEUE_CAP + ev_mod._EVICT_AFTER, 0.0, start=5000)
    publish_wall = time.perf_counter() - t0
    assert h.flush(60.0)
    assert stuck.evicted and stuck.closed
    assert dropped.value(reason="evicted") == evict_before + 1
    slow_drops = dropped.value(reason="slow_consumer") - slow_before
    assert slow_drops >= ev_mod._EVICT_AFTER
    assert publish_wall < 5.0, (
        f"publisher spent {publish_wall:.2f}s — it must never block on a "
        "wedged consumer"
    )
    h.close()

    return {
        "metric": "sse_fanout",
        "value": round(rate, 1),
        "unit": (
            f"deliveries/sec ({subs_big} subscribers, paced head events)"
        ),
        "vs_baseline": vs_baseline,
        "baseline_control": (
            "per-subscriber re-serialization (naive tier) over the same "
            f"{subs_big}-subscriber population, same run — the shared-"
            "frame economics"
        ),
        "config": {
            "subscribers": subs_big,
            "events": events_big,
            "pace_ms": pace_big_s * 1000,
            "sentinel_drainers": sentinels,
            "lag_p50_ms": round(lag_p50_ms, 2),
            "lag_p99_ms": round(lag_p99_ms, 2),
            "p99_cap_ms": p99_cap_ms,
            "zero_drop_phase": {
                "subscribers": subs_small,
                "events": events_small,
                "drops": 0,
            },
            "eviction_phase": {
                "slow_consumer_drops": int(slow_drops),
                "evictions": 1,
                "publish_wall_s": round(publish_wall, 3),
            },
            "queue_cap": ev_mod._QUEUE_CAP,
            "evict_after": ev_mod._EVICT_AFTER,
        },
    }


_VC_STAGES = (
    "vc_duty_cycle",
    "vc_fetch",
    "vc_assemble",
    "vc_protect",
    "vc_sign_batch",
    "vc_publish",
)


def _build_vc_state(n):
    """A resident n-validator state parked at an epoch start, with
    DISTINCT per-validator pubkeys and matching secret-key scalars.

    `_build_epoch_state` clones validator 0's pubkey across the registry
    (epoch sweeps never look at it) — the VC duty cycle DOES: duties
    resolve index->pubkey and the store signs by pubkey, so every key
    must be unique. Registry identities are synthetic (index-derived 48
    bytes): deriving n real G1 pubkeys is n scalar muls of setup the
    duty cycle never touches, while signing identity is sk-only — the
    per-key oracle and the batch path sign with the same scalars either
    way, so the bit-identity assertion is unaffected."""
    import hashlib as _h
    from dataclasses import replace

    from lighthouse_tpu.beacon_chain.chain import _make_persistent
    from lighthouse_tpu.crypto import bls
    from lighthouse_tpu.crypto.bls import R
    from lighthouse_tpu.state_processing import interop_genesis_state
    from lighthouse_tpu.state_processing.registry_columns import (
        registry_columns_for,
    )
    from lighthouse_tpu.types.chain_spec import minimal_spec
    from lighthouse_tpu.types.eth_spec import MinimalEthSpec

    class _VcBenchSpec(MinimalEthSpec):
        """Minimal preset with the committee axis widened 4 -> 8.
        Minimal's 4-committee cap would shear 100k keys into 32
        committees of 3125 — over the SSZ Bitlist limit
        (MAX_VALIDATORS_PER_COMMITTEE = 2048), a shape no preset can
        express. 8/slot gives 64 committees of ~1562: legal, and the
        mainnet-like regime where a whole committee shares one
        AttestationData (the grouping the batch signer amortizes)."""

        MAX_COMMITTEES_PER_SLOT = 8

    E = _VcBenchSpec
    bls.set_backend("host")  # real signing: the metric IS the signing
    spec = replace(minimal_spec(), altair_fork_epoch=0)
    base = interop_genesis_state(
        bls.interop_keypairs(8), 1_600_000_000, b"\x42" * 32, spec, E
    )
    v0 = base.validators[0]
    vs, bal, sks = [], [], []
    for i in range(n):
        v = v0.copy()
        v.pubkey = (
            _h.sha256(b"vc_pk" + i.to_bytes(4, "little")).digest()
            + i.to_bytes(16, "little")
        )
        v.withdrawal_credentials = i.to_bytes(32, "little")
        vs.append(v)
        bal.append(32_000_000_000)
        sks.append(
            bls.SecretKey(
                1
                + int.from_bytes(
                    _h.sha256(b"vc_sk" + i.to_bytes(4, "little")).digest(),
                    "big",
                )
                % (R - 1)
            )
        )
    base.validators = vs
    base.balances = bal
    base.previous_epoch_participation = bytearray(n)
    base.current_epoch_participation = bytearray(n)
    base.inactivity_scores = [0] * n
    # epoch-3 start: the epoch's 8 duty slots never cross a boundary, so
    # per-slot head advances stay slot-processing, not epoch transitions
    # (epoch_transition_100k already owns that number)
    base.slot = 3 * E.SLOTS_PER_EPOCH
    _make_persistent(base)
    cols = registry_columns_for(base)
    if cols is not None:  # None under LIGHTHOUSE_TPU_RESIDENT_COLUMNS=0
        cols.refresh(base)
    return base, spec, E, sks


def bench_vc_epoch_100k(jax):
    """One epoch's full attestation duty cycle at 100k keys in ONE VC
    process (PR 19 tentpole): per slot, the batch pipeline fetches duties
    (one bulk epoch-duty-table fetch, cached for the epoch), advances the
    head state, assembles ONE AttestationData per committee, runs
    slashing protection as one transaction, signs through the grouped
    fixed-base batch signer, and publishes — 100k real BLS signatures
    over the epoch's 32 distinct messages.

    vs_baseline is the retained per-key oracle (`sign_attestation` per
    duty: domain + hash_tree_root + per-entry sqlite commit + hash_to_g2
    + generic pt_mul) on a 1/64 key subsample, same run, scaled to n —
    composed with the batch run's OWN fetch/assemble/publish cost so the
    shared stages are counted once at measured cost instead of being
    inflated by the extrapolation. In-bench asserts: every subsample
    signature bit-identical between the two paths, slashing-DB rows for
    the subsample identical, zero refusals on both, and (full scale)
    >=5x over the composed oracle estimate."""
    import gc
    from types import SimpleNamespace

    from lighthouse_tpu.metrics import REGISTRY
    from lighthouse_tpu.validator_client import (
        AttestationService,
        DutiesService,
        LocalBeaconNode,
        LocalKeystoreSigner,
        ValidatorStore,
    )

    n = 2_000 if SMOKE else 100_000
    state, spec, E, sks = _build_vc_state(n)
    pk_of = [bytes(v.pubkey) for v in state.validators]

    class _RecordingNode(LocalBeaconNode):
        """LocalBeaconNode over a chain-shaped shim: real bulk-duties
        surface (the epoch duty table), but publishes are counted, not
        imported — the measurement is the VC pipeline, not block-side
        attestation processing (attestation_batch owns that)."""

        def __init__(self, st):
            super().__init__(SimpleNamespace(head_state=st, E=E))
            self.published = 0

        def publish_attestations(self, attestations):
            self.published += len(attestations)

    node = _RecordingNode(state)
    store = ValidatorStore()
    t0 = time.perf_counter()
    for pk, sk in zip(pk_of, sks):
        store.add_validator(pk, LocalKeystoreSigner(sk))
    _partial(stage="register", keys=n, s=round(time.perf_counter() - t0, 2))
    duties_svc = DutiesService(store, node, spec, E)
    svc = AttestationService(duties_svc, store, node, spec, E)

    head = b"\x42" * 32
    start = int(state.slot)
    refusals = REGISTRY.counter("vc_slashing_protection_refusals_total")
    refusals_before = refusals.value()
    spans_before = _span_totals(_VC_STAGES)

    batch_sigs = {}
    states_by_slot = {}
    slot_walls = []
    t0 = time.perf_counter()
    for slot in range(start, start + E.SLOTS_PER_EPOCH):
        s0 = time.perf_counter()
        out = svc.attest(slot, head)
        slot_walls.append(round(time.perf_counter() - s0, 3))
        _partial(slot=slot - start + 1, of=E.SLOTS_PER_EPOCH,
                 s=slot_walls[-1], sigs=len(out))
        # follow the chain: the advanced state becomes the next head, so
        # each fetch advances one slot (the steady-state VC shape)
        states_by_slot[slot] = svc._last_attested[1]
        node.chain.head_state = svc._last_attested[1]
        epoch_duties = duties_svc.attester_duties(
            (slot // E.SLOTS_PER_EPOCH)
        )  # cached: the ONE bulk fetch happened at the epoch's first slot
        slot_duties = [d for d in epoch_duties if d.slot == slot]
        assert len(out) == len(slot_duties), "refusal in a clean run"
        for duty, att in zip(slot_duties, out):
            batch_sigs[duty.validator_index] = bytes(att.signature)
        del out
    wall = time.perf_counter() - t0
    stages = _span_deltas(spans_before, _span_totals(_VC_STAGES))
    assert node.published == n, f"published {node.published}, expected {n}"
    assert refusals.value() == refusals_before, "refusals in a clean run"
    keyed_batch_s = sum(
        stages[s]["mean_ms"] / 1000 * stages[s]["samples"]
        for s in ("vc_protect", "vc_sign_batch")
        if s in stages
    )
    gc.collect()

    # -- per-key oracle on a 1/64 subsample, same states, same duties ----
    epoch_duties = duties_svc.attester_duties(start // E.SLOTS_PER_EPOCH)
    ctrl_set = set(range(0, n, 64))  # uniform over committees via shuffle
    ctrl_jobs = [d for d in epoch_duties if d.validator_index in ctrl_set]
    assert len(ctrl_jobs) == len(ctrl_set), "every key has exactly one duty"
    ctrl_store = ValidatorStore()
    for vi in sorted(ctrl_set):
        ctrl_store.add_validator(pk_of[vi], LocalKeystoreSigner(sks[vi]))
    ctrl_sigs = {}
    t0 = time.perf_counter()
    for duty in ctrl_jobs:
        st = states_by_slot[duty.slot]
        data = svc._attestation_data(st, duty.slot, head, duty.committee_index)
        ctrl_sigs[duty.validator_index] = ctrl_store.sign_attestation(
            pk_of[duty.validator_index], data, st, spec, E
        )
    ctrl_s = time.perf_counter() - t0
    _partial(stage="control", keys=len(ctrl_jobs), s=round(ctrl_s, 2))

    # composed oracle estimate: shared fetch/assemble/publish at the
    # batch run's own measured cost, keyed stages at the per-key rate
    ctrl_scaled = ctrl_s * (n / len(ctrl_jobs))
    oracle_epoch_s = (wall - keyed_batch_s) + ctrl_scaled
    speedup = oracle_epoch_s / wall

    # -- riding differential asserts -------------------------------------
    for vi in ctrl_set:
        assert batch_sigs[vi] == ctrl_sigs[vi], (
            f"batch signature for validator {vi} diverges from per-key"
        )
    q = (
        "SELECT a.source_epoch, a.target_epoch, a.signing_root "
        "FROM signed_attestations a JOIN validators v "
        "ON a.validator_id = v.id WHERE v.pubkey = ? "
        "ORDER BY a.target_epoch"
    )
    for vi in ctrl_set:
        batch_rows = store.slashing_db._conn.execute(q, (pk_of[vi],)).fetchall()
        ctrl_rows = ctrl_store.slashing_db._conn.execute(
            q, (pk_of[vi],)
        ).fetchall()
        assert batch_rows == ctrl_rows, f"slashing rows diverge for {vi}"
    if not SMOKE:
        assert speedup >= 5.0, (
            f"batch duty cycle {speedup:.2f}x per-key oracle — below the 5x "
            "floor"
        )

    distinct = len({(d.slot, d.committee_index) for d in epoch_duties})
    return {
        "metric": "vc_epoch_100k",
        "value": round(wall, 2),
        "unit": f"s/epoch ({n} keys, full attestation duty cycle)",
        "vs_baseline": round(speedup, 2),
        "baseline_control": (
            "per-key oracle (sign_attestation per duty: domain + "
            "hash_tree_root + per-entry sqlite commit + hash_to_g2 + "
            "generic pt_mul) on a 1/64 subsample x64, same run, composed "
            "with the batch run's own shared-stage cost"
        ),
        "config": {
            "keys": n,
            "signatures": node.published,
            "signatures_per_sec": round(n / wall, 1),
            "distinct_messages": distinct,
            "slot_walls_s": slot_walls,
            "keyed_stages_s": round(keyed_batch_s, 2),
            "control_keys": len(ctrl_jobs),
            "control_s": round(ctrl_s, 2),
            "control_scaled_s": round(ctrl_scaled, 2),
            "oracle_epoch_est_s": round(oracle_epoch_s, 2),
            "refusals": 0,
        },
        "stages": stages,
        "spread": {
            "median_s": wall, "min_s": wall, "max_s": wall, "trials": 1,
        },
    }


_METRICS = {
    "merkle": bench_merkle,
    "pairing": bench_pairing,
    "block_import": bench_block_import,
    "block_production": bench_block_production,
    "epoch_transition": bench_epoch_transition,
    "epoch_transition_1m": bench_epoch_transition_1m,
    "state_root": bench_state_root,
    "epoch_reroot": bench_epoch_reroot,
    "kzg": bench_kzg,
    "da_verify": bench_da_verify,
    "da_withholding": bench_da_withholding,
    "bls": bench_bls,
    "sync_catchup": bench_sync_catchup,
    "gossip_soak": bench_gossip_soak,
    "testnet_soak": bench_testnet_soak,
    "attestation_batch": bench_attestation_batch,
    "fork_choice": bench_fork_choice,
    "op_pool": bench_op_pool,
    "slasher_ingest": bench_slasher_ingest,
    "api_throughput": bench_api_throughput,
    "sse_fanout": bench_sse_fanout,
    "vc_epoch_100k": bench_vc_epoch_100k,
    "checkpoint_boot_s": bench_checkpoint_boot,
    "store_soak": bench_store_soak,
    "testnet_churn_soak": bench_testnet_churn_soak,
}


def _metric_cap(name: str, default: float) -> float:
    """Per-metric wall-clock cap, overridable via BENCH_TIMEOUT_<METRIC>
    (seconds; 0 skips the metric). On 1-core images the device-compile
    metrics (kzg, bls) blow any default cap — BENCH_TIMEOUT_KZG=0
    BENCH_TIMEOUT_BLS=0 turns their recurring `timed out` errors into an
    explicit, documented skip; on TPU hosts a larger override buys the
    cold compile a real chance instead."""
    raw = os.environ.get(f"BENCH_TIMEOUT_{name.upper()}")
    if raw is None:
        return default
    try:
        return float(raw)
    except ValueError:
        return default


def _collect_partials(stdout) -> list:
    """Pull `PARTIAL {...}` progress lines out of a (possibly dead)
    subprocess's stdout."""
    if not stdout:
        return []
    if isinstance(stdout, bytes):
        stdout = stdout.decode(errors="replace")
    out = []
    for line in stdout.splitlines():
        if line.startswith("PARTIAL "):
            try:
                out.append(json.loads(line[len("PARTIAL "):]))
            except ValueError:
                pass
    return out


def _run_one(name: str) -> int:
    """Subprocess entry: run ONE metric, print its JSON. Under --profile
    (BENCH_PROFILE=1, inherited from the parent) the stack sampler runs
    across the metric's trials and the top hotspot stacks per trace root
    ride along under `hotspots`; the result is flagged `profiled` so
    --compare refuses to score it against an unprofiled baseline."""
    import jax

    if os.environ.get("BENCH_PROFILE") != "1":
        print(json.dumps(_METRICS[name](jax)))
        return 0
    from lighthouse_tpu.metrics.profiler import StackProfiler

    prof = StackProfiler()
    prof.start()
    try:
        result = _METRICS[name](jax)
    finally:
        prof.stop()
    result["hotspots"] = prof.top_stacks(n=5)
    result["profile"] = {"hz": prof.hz, "samples": prof.samples_total}
    result["profiled"] = True
    print(json.dumps(result))
    return 0


def main():
    # Hard wall-clock budget (BENCH_BUDGET_S, default 20 min — the driver's
    # kill window ate round 3's 50-min default). Each metric runs in a
    # subprocess sharing the persistent compile cache. The driver parses the
    # LAST complete JSON line of the tail, so this loop prints a well-formed
    # combined line after EVERY metric completes: a kill at any point leaves
    # the best result so far on stdout instead of erasing finished work.
    # Cheap secondaries run first; the BLS headline runs last with whatever
    # budget remains and, when it completes, takes over the final line.
    import subprocess

    _refuse_sanitize_mode()
    budget = float(os.environ.get("BENCH_BUDGET_S", "1200"))
    deadline = time.monotonic() + budget
    details = []
    errors = {}

    def run_metric(name: str, cap: float):
        # budget exhaustion first: a cap that went non-positive only
        # because the deadline passed is NOT an explicit env-var skip
        remaining = deadline - time.monotonic()
        if remaining <= 30:
            errors[name] = "skipped: budget exhausted"
            return None
        if cap <= 0:
            errors[name] = (
                f"skipped: BENCH_TIMEOUT_{name.upper()}=0 (explicitly disabled)"
            )
            return None
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--metric", name],
                capture_output=True,
                text=True,
                timeout=min(cap, remaining),
                cwd=os.path.dirname(os.path.abspath(__file__)),
            )
        except subprocess.TimeoutExpired as e:
            # keep whatever per-trial/per-chunk timings completed: a timed-out
            # metric still yields data instead of a bare error string
            partial = _collect_partials(e.stdout)
            msg = f"timed out (> {min(cap, remaining):.0f}s)"
            errors[name] = {"error": msg, "partial": partial} if partial else msg
            return None
        if proc.returncode != 0:
            tail = (proc.stderr or "").strip().splitlines()[-3:]
            msg = f"exit {proc.returncode}: {' | '.join(tail)}"
            # a crashed metric (OOM kill, assert) salvages its completed
            # trial/chunk timings exactly like a timed-out one
            partial = _collect_partials(proc.stdout)
            errors[name] = {"error": msg, "partial": partial} if partial else msg
            return None
        try:
            # last stdout line is the metric JSON (warnings may precede)
            return json.loads(proc.stdout.strip().splitlines()[-1])
        except (ValueError, IndexError):
            errors[name] = f"unparseable output: {proc.stdout[-200:]!r}"
            return None

    def emit(head):
        """Print the combined line for the results gathered so far."""
        out = dict(head)
        out["details"] = [d for d in details if d is not head]
        if errors:
            out["errors"] = dict(errors)
        if os.environ.get("BENCH_PROFILE") == "1":
            # profiled trials carry sampling overhead (bounded ≤1.10× by
            # perf_smoke, but real): flag the whole line so --compare and
            # baseline rebasing skip it, like the sanitize-mode exclusion
            out["profiled"] = True
        print(json.dumps(out), flush=True)

    secondary_caps = {
        "merkle": 180,
        "pairing": 60,  # host microbench, no compiles
        "block_import": 90,
        # 1M-registry genesis (~15 s) + untimed park to the boundary + 3
        # cold productions (each pays the boundary transition inline) +
        # one pre-advance + 3 pre-advanced productions;
        # BENCH_TIMEOUT_BLOCK_PRODUCTION overrides (0 = explicit skip)
        "block_production": 420,
        "epoch_transition": 120,
        # 1M-validator fixture build (~15 s) + columns cold build + 3
        # resident trials + the subsampled legacy-oracle control;
        # BENCH_TIMEOUT_EPOCH_TRANSITION_1M overrides (0 = explicit skip)
        "epoch_transition_1m": 420,
        "state_root": 300,  # 1M-validator build + 3 cold columnar rebuilds
        "epoch_reroot": 300,  # 1M mass-churn full-rebuild re-roots
        "kzg": 240,  # metric 4; compile served by the warmed cache
        # 768-cell build is disk-cached after the first run; 3 batched
        # trials + 2 scalar-oracle subsample controls + tamper parity
        "da_verify": 300,
        # two-regime withholding fleet scenario (refusal->finality,
        # >=50%->reconstruction import); fake_crypto, no compiles
        "da_withholding": 300,
        "sync_catchup": 120,  # fake_crypto loopback pair; no compiles
        # 3 flood trials (2 flooder services each) + 3 flood-free
        # controls; fake_crypto, no compiles
        "gossip_soak": 180,
        # N-node fleet boot + healthy soak epochs + partition-heal
        # cycles with convergence waits; fake_crypto, no compiles
        "testnet_soak": 300,
        # 16k-validator fixture + 3 columnar trials + 2 scalar-oracle
        # controls (the controls dominate: ~65k per-validator Python
        # iterations each)
        "attestation_batch": 120,
        # 1M-vote columnar rounds are ~150 ms; the 1/16-subsample scalar
        # oracle (62.5k dict-walked votes per round) dominates
        "fork_choice": 120,
        # 500k-attestation pool build (~20 s of insert-time hashing) + 3
        # flat packs + the 31k-candidate rescan reference controls
        "op_pool": 240,
        # 2x 2048-aggregate flood build + 3 pre-warmed engines (one warm
        # epoch each) + 3 timed flood cycles + 2 scalar-subsample
        # controls; BENCH_TIMEOUT_SLASHER_INGEST overrides (0 = skip)
        "slasher_ingest": 240,
        # 1M fixture build + 3 cold full-table assemblies + 2 full-table
        # per-object oracle controls (those dominate) + hot/paginated
        # sweeps + the workers={1,4} forked-replica axis (two server
        # boots, HTTP load, full-table digest bursts, a head-refresh
        # rotation); BENCH_TIMEOUT_API_THROUGHPUT overrides (0 = skip)
        "api_throughput": 540,
        # pure-host fan-out: 1k/10k subscriber phases at paced cadence +
        # the per-subscriber serialization control + the eviction phase;
        # BENCH_TIMEOUT_SSE_FANOUT overrides (0 = skip)
        "sse_fanout": 180,
        # 100k-key fixture + registration + one full epoch of REAL host
        # BLS batch signing (~32 fixed-base tables + 100k window muls)
        # + the 1/64 per-key-oracle control (generic pt_mul dominates);
        # BENCH_TIMEOUT_VC_EPOCH_100K overrides (0 = explicit skip)
        "vc_epoch_100k": 600,
        # 8-epoch source chain + 3 checkpoint boots (3 HTTP round-trips
        # each) + a full backfill per trial; fake_crypto, no compiles
        "checkpoint_boot_s": 180,
        # two 10-epoch harness chains (migration ON + OFF control),
        # hot-store byte sampling per epoch; fake_crypto, no compiles
        "store_soak": 240,
        # disk-backed fleet boot + finality warmup + kill/restart rounds
        # with reconvergence waits; fake_crypto, no compiles
        "testnet_churn_soak": 420,
    }
    for name, cap in secondary_caps.items():
        cap = _metric_cap(name, cap)
        result = run_metric(name, cap=min(cap, deadline - time.monotonic()))
        if result is not None:
            details.append(result)
            emit(details[0])  # provisional headline: first survivor

    head = run_metric(
        "bls", cap=_metric_cap("bls", deadline - time.monotonic())
    )
    if head is None and not details:
        head = {"metric": "bench_failed", "value": 0, "unit": "",
                "vs_baseline": 0}
    emit(head if head is not None else details[0])


def _load_bench_entries(path: str) -> tuple[dict, bool]:
    """Flatten one bench JSON (a combined line, or a driver BENCH_rXX.json
    wrapper whose `parsed` holds it) into {metric: entry}; second element
    reports whether the run was profiled (not comparable)."""
    with open(path) as f:
        raw = json.load(f)
    if "parsed" in raw and isinstance(raw["parsed"], dict):
        raw = raw["parsed"]
    entries: dict[str, dict] = {}

    def add(e):
        if (
            isinstance(e, dict)
            and isinstance(e.get("metric"), str)
            and isinstance(e.get("value"), (int, float))
        ):
            entries[e["metric"]] = e
            # axis sub-metrics (e.g. api_throughput_workers{1,4}) compare
            # individually — each carries its own unit for direction
            for s in e.get("sub_metrics", ()):
                if isinstance(s, dict):
                    add(s)

    add(raw)
    for d in raw.get("details", ()):
        add(d)
    profiled = bool(raw.get("profiled")) or any(
        e.get("profiled") for e in entries.values()
    )
    return entries, profiled


def _rel_spread(entry: dict) -> float:
    """(max-min)/median of a metric's trial spread — its noise floor.
    Metrics without a recorded spread (e.g. block_import_ms) report 0
    and fall back to the bare threshold."""
    s = entry.get("spread")
    if not isinstance(s, dict):
        return 0.0
    try:
        med = float(s["median_s"])
        return (float(s["max_s"]) - float(s["min_s"])) / med if med else 0.0
    except (KeyError, TypeError, ValueError, ZeroDivisionError):
        return 0.0


# Explicit per-metric regression directions, consulted BEFORE the unit
# heuristic below. Slope/size metrics need this: store_soak's unit is
# "bytes/epoch" — the "/s"-style probes can't classify it, and a growth
# slope regresses UP no matter how its unit reads. True = higher is
# better, False = lower is better; metrics not listed fall back to the
# unit heuristic.
_METRIC_DIRECTIONS = {
    "checkpoint_boot_s": False,  # boot latency
    "store_soak": False,  # final hot-store bytes, migration ON
    "store_soak_migration_off": False,  # control (migration OFF)
    "testnet_churn_soak": True,  # finalization throughput under churn
    "testnet_churn_hot_growth": False,  # bounded-store multiple
}


def _higher_is_better(unit: str) -> bool:
    # throughputs count up: "leaves/sec", "cells/s (…)", and testnet_soak's
    # "slots finalized per wall-second" — the padded "/s " probe matches a
    # bare "/s" mid- or end-of-string without catching "ms/…" latencies
    u = (unit or "") + " "
    return "/sec" in u or "/s " in u or "per wall-second" in u


def compare_runs(old_path: str, new_path: str, threshold: float = 0.15) -> int:
    """`bench.py --compare OLD.json NEW.json`: the regression sentinel.
    For every metric present in both files, compute the regression
    fraction in the metric's own direction (throughputs regress down,
    latencies regress up) and flag it when it exceeds
    max(threshold, (old_spread + new_spread) / 2) — spread-aware, so a
    metric whose own trials wobble 20% needs a >20% move to fire.
    Prints a per-metric delta table; exits 1 on any REGRESSED metric,
    2 when either side is a profiled (non-comparable) run."""
    old, old_prof = _load_bench_entries(old_path)
    new, new_prof = _load_bench_entries(new_path)
    if old_prof or new_prof:
        which = " and ".join(
            p for p, flag in ((old_path, old_prof), (new_path, new_prof)) if flag
        )
        print(
            f"refusing to compare: {which} recorded under --profile "
            "(sampler overhead rides the numbers; re-run without it)"
        )
        return 2
    shared = [m for m in old if m in new]
    if not shared:
        print(f"no shared metrics between {old_path} and {new_path}")
        return 2
    rows = []
    regressed = []
    for m in sorted(shared):
        o, n = old[m], new[m]
        ov, nv = float(o["value"]), float(n["value"])
        if ov == 0:
            rows.append((m, ov, nv, "n/a", "n/a", "SKIP (old=0)"))
            continue
        direction = _METRIC_DIRECTIONS.get(m)
        higher = (
            direction
            if direction is not None
            else _higher_is_better(n.get("unit") or o.get("unit") or "")
        )
        # regression fraction, positive = worse in this metric's direction
        r = (ov - nv) / ov if higher else (nv - ov) / ov
        tol = max(threshold, (_rel_spread(o) + _rel_spread(n)) / 2.0)
        if r > tol:
            verdict = "REGRESSED"
            regressed.append(m)
        elif -r > tol:
            verdict = "improved"
        else:
            verdict = "ok"
        delta_pct = (nv - ov) / ov * 100.0
        rows.append(
            (m, ov, nv, f"{delta_pct:+.1f}%", f"±{tol * 100:.0f}%", verdict)
        )
    widths = [max(len(str(r[i])) for r in rows + [("metric", "old", "new",
               "delta", "tolerance", "verdict")]) for i in range(6)]
    header = ("metric", "old", "new", "delta", "tolerance", "verdict")
    for row in [header] + rows:
        print("  ".join(str(c).ljust(w) for c, w in zip(row, widths)))
    only_old = sorted(set(old) - set(new))
    only_new = sorted(set(new) - set(old))
    if only_old:
        print(f"only in {old_path}: {', '.join(only_old)}")
    if only_new:
        print(f"only in {new_path}: {', '.join(only_new)}")
    if regressed:
        print(f"REGRESSION: {', '.join(regressed)} "
              f"(median worse by more than the spread-aware threshold)")
        return 1
    print(f"ok: {len(shared)} shared metrics within threshold")
    return 0


def _refuse_sanitize_mode():
    """Sanitize mode write-guards buffers and runs wide-dtype checks on
    every sweep — numbers recorded under it are not comparable to the
    baselines (BENCH_NOTES.md "Sanitize mode"). Refuse, loudly."""
    if os.environ.get("LIGHTHOUSE_TPU_SANITIZE") == "1":
        print(
            json.dumps(
                {
                    "error": (
                        "refusing to record timed trials with "
                        "LIGHTHOUSE_TPU_SANITIZE=1 set — sanitize mode is "
                        "excluded from benchmarks; unset it and re-run"
                    )
                }
            )
        )
        sys.exit(2)


def _parse_args(argv: list[str]) -> list[str]:
    """Strip --bls-backend / --profile (both propagated via env to the
    metric subprocesses)."""
    out = []
    i = 0
    while i < len(argv):
        if argv[i] == "--bls-backend":
            if i + 1 >= len(argv):
                raise SystemExit("--bls-backend requires a value (host|tpu)")
            os.environ["BENCH_BLS_BACKEND"] = argv[i + 1]
            i += 2
        elif argv[i].startswith("--bls-backend="):
            os.environ["BENCH_BLS_BACKEND"] = argv[i].split("=", 1)[1]
            i += 1
        elif argv[i] == "--profile":
            os.environ["BENCH_PROFILE"] = "1"
            i += 1
        else:
            out.append(argv[i])
            i += 1
    return out


if __name__ == "__main__":
    argv = _parse_args(sys.argv[1:])
    if argv and argv[0] == "--compare":
        # pure file comparison: no metrics run, sanitize mode irrelevant.
        # Bad arity must ERROR, not fall through into a full bench run
        if len(argv) != 3:
            raise SystemExit("usage: bench.py --compare OLD.json NEW.json")
        sys.exit(compare_runs(argv[1], argv[2]))
    # covers the --metric subprocess entry too: no timed trial ever runs
    # with the sanitizer's guards armed
    _refuse_sanitize_mode()
    if len(argv) == 2 and argv[0] == "--metric":
        sys.exit(_run_one(argv[1]))
    sys.exit(main())
